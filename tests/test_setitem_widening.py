"""Setitem widening (VERDICT r3 #8): value-broadcast writes, mixed
advanced+basic keys, boolean masks, negative steps, and dtype-casting
writes — numpy ground truth across splits on the 8-device mesh (the
remaining width of the reference's setitem family,
heat/core/tests/test_dndarray.py).
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS_2D = [None, 0, 1]


def _roundtrip(base, key, value, split):
    """Apply the same write to numpy and heat; compare the full array."""
    want = base.copy()
    want[key] = value
    a = ht.array(base.copy(), split=split)
    a[key] = value
    np.testing.assert_allclose(a.numpy(), want, rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_scalar_broadcast_into_slab(split):
    base = np.arange(48, dtype=np.float32).reshape(8, 6)
    _roundtrip(base, (slice(2, 6), slice(1, 4)), 7.5, split)
    _roundtrip(base, (slice(None), 2), -1.0, split)
    _roundtrip(base, (3,), 0.0, split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_row_vector_broadcast(split):
    base = np.zeros((8, 6), np.float32)
    _roundtrip(base, slice(1, 7), np.arange(6, dtype=np.float32), split)
    _roundtrip(base, (slice(None), slice(0, 3)), np.arange(3, dtype=np.float32), split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_column_vector_broadcast(split):
    base = np.zeros((8, 6), np.float32)
    _roundtrip(base, (slice(2, 5),), np.arange(3, dtype=np.float32).reshape(3, 1), split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_mixed_advanced_basic(split):
    base = np.arange(64, dtype=np.float32).reshape(8, 8)
    rows = np.array([0, 3, 5])
    _roundtrip(base, (rows, slice(2, 6)), 9.0, split)  # fancy rows, basic cols
    _roundtrip(base, (slice(1, 7), np.array([1, 4])), -3.0, split)
    _roundtrip(
        base, (rows, slice(0, 4)), np.arange(12, dtype=np.float32).reshape(3, 4), split
    )


@pytest.mark.parametrize("split", SPLITS_2D)
def test_fancy_fancy_pairs(split):
    base = np.zeros((8, 8), np.float32)
    rows = np.array([1, 2, 6])
    cols = np.array([0, 5, 7])
    _roundtrip(base, (rows, cols), np.array([1.0, 2.0, 3.0], np.float32), split)
    _roundtrip(base, (rows, cols), 4.0, split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_boolean_mask_writes(split):
    base = np.arange(48, dtype=np.float32).reshape(8, 6)
    mask = (base % 5 == 0)
    _roundtrip(base, mask, -1.0, split)
    row_mask = np.array([True, False] * 4)
    _roundtrip(base, row_mask, 0.0, split)
    # mask with a matching-length value vector
    _roundtrip(base, mask, np.arange(mask.sum(), dtype=np.float32), split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_negative_step_writes(split):
    base = np.arange(48, dtype=np.float32).reshape(8, 6)
    _roundtrip(base, (slice(None, None, -1),), np.arange(48, dtype=np.float32).reshape(8, 6), split)
    _roundtrip(base, (slice(6, 1, -2), slice(None)), 5.0, split)


@pytest.mark.parametrize("split", SPLITS_2D)
def test_value_dtype_cast_on_write(split):
    base = np.arange(24, dtype=np.int32).reshape(4, 6)
    _roundtrip(base, (slice(0, 2),), 7.9, split)  # float into int casts
    basef = np.arange(24, dtype=np.float32).reshape(4, 6)
    _roundtrip(basef, (slice(0, 2),), np.arange(12).reshape(2, 6), split)  # int into float


@pytest.mark.parametrize("split", SPLITS_2D)
def test_dndarray_value_with_different_split(split):
    base = np.zeros((8, 6), np.float32)
    val = np.arange(18, dtype=np.float32).reshape(3, 6)
    want = base.copy()
    want[2:5] = val
    for vsplit in (None, 0, 1):
        a = ht.array(base.copy(), split=split)
        a[2:5] = ht.array(val, split=vsplit)
        np.testing.assert_allclose(a.numpy(), want)


@pytest.mark.parametrize("split", [None, 0])
def test_ellipsis_and_newaxis_keys(split):
    base = np.arange(40, dtype=np.float32).reshape(8, 5)
    _roundtrip(base, (Ellipsis, 2), 1.5, split)
    _roundtrip(base, (Ellipsis,), 0.25, split)
    a3 = np.arange(60, dtype=np.float32).reshape(3, 4, 5)
    _roundtrip(a3, (Ellipsis, slice(1, 3)), -2.0, split)
    _roundtrip(a3, (1, Ellipsis), 3.0, split)


@pytest.mark.parametrize("split", [None, 0])
def test_uneven_extent_writes(split):
    # 13 rows over 8 devices: writes crossing the padded tail
    base = np.arange(13 * 3, dtype=np.float32).reshape(13, 3)
    _roundtrip(base, (slice(10, 13),), 9.0, split)
    _roundtrip(base, (np.array([12, 0, 7]),), np.zeros((3, 3), np.float32), split)
    _roundtrip(base, (12, 2), 123.0, split)
