"""Iterative/triangular solvers, analog of heat/core/linalg/solver.py.

``cg`` (solver.py:16-66) and ``lanczos`` (:69-274) are compositions of the
distributed ops API and port structurally; ``solve_triangular`` (:275-463)
— blocked backward substitution with Bcasts in the reference — lowers to
XLA's triangular solve over the sharded operand.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from .basics import matmul, transpose

__all__ = ["cg", "lanczos", "solve_triangular"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD systems (solver.py:16)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be DNDarrays, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    r = b - matmul(A, x0)
    p = r
    rsold = matmul(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / matmul(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = matmul(r, r)
        if float(jnp.sqrt(rsnew._dense())) < 1e-10:
            if out is not None:
                out._replace(x.larray_padded)
                return out
            return x
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out._replace(x.larray_padded)
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric/Hermitian matrix
    (solver.py:69): m Krylov steps with full reorthogonalization.
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, but was {type(A)}")
    if not isinstance(m, int) or m <= 0:
        raise TypeError(f"m must be a positive integer, got {m}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")

    n = A.shape[0]
    dense_A = A._dense()
    dtype = dense_A.dtype
    is_complex = types.heat_type_is_complexfloating(A.dtype)

    from .. import random as ht_random

    if v0 is None:
        v = ht_random.randn(n, dtype=types.canonical_heat_type(jnp.float32), comm=A.comm)._dense().astype(dtype)
        v = v / jnp.linalg.norm(v)
    else:
        v = v0._dense().astype(dtype)

    V = jnp.zeros((n, m), dtype=dtype)
    T = jnp.zeros((m, m), dtype=jnp.float32)
    V = V.at[:, 0].set(v)

    beta = 0.0
    v_prev = jnp.zeros_like(v)
    for j in range(m):
        w = jnp.matmul(dense_A, V[:, j], precision=jax.lax.Precision.HIGHEST)
        alpha = jnp.real(jnp.vdot(V[:, j], w)) if is_complex else jnp.vdot(V[:, j], w)
        w = w - alpha * V[:, j] - beta * v_prev
        # full reorthogonalization (solver.py:153+)
        w = w - jnp.matmul(V[:, : j + 1], jnp.matmul(jnp.conj(V[:, : j + 1]).T, w, precision=jax.lax.Precision.HIGHEST), precision=jax.lax.Precision.HIGHEST)
        T = T.at[j, j].set(alpha.astype(jnp.float32))
        if j < m - 1:
            beta = jnp.linalg.norm(w)
            T = T.at[j, j + 1].set(beta.astype(jnp.float32))
            T = T.at[j + 1, j].set(beta.astype(jnp.float32))
            v_prev = V[:, j]
            V = V.at[:, j + 1].set(jnp.where(beta > 1e-10, w / jnp.maximum(beta, 1e-30), w))

    V_res = DNDarray.from_dense(V, A.split, A.device, A.comm)
    T_res = DNDarray.from_dense(T, None, A.device, A.comm)
    if V_out is not None:
        V_out._replace(V_res.larray_padded)
        V_res = V_out
    if T_out is not None:
        T_out._replace(T_res.larray_padded)
        T_res = T_out
    return V_res, T_res


def solve_triangular(A: DNDarray, b: DNDarray) -> DNDarray:
    """Solve A x = b for upper-triangular A (solver.py:275)."""
    sanitize_in(A)
    sanitize_in(b)
    if A.ndim < 2 or A.shape[-1] != A.shape[-2]:
        raise ValueError("A must be a (batch of) square upper triangular matrix")
    import jax.scipy.linalg as jsl

    a_dense = A._dense()
    b_dense = b._dense()
    if not types.heat_type_is_inexact(A.dtype):
        a_dense = a_dense.astype(jnp.float32)
        b_dense = b_dense.astype(jnp.float32)
    result = jsl.solve_triangular(a_dense, b_dense, lower=False)
    return DNDarray.from_dense(result, b.split, b.device, b.comm)
