"""Kernel roofline observatory: every production dispatch is a measurement.

The paper's promise is "as fast as the hardware allows", yet until this
module nothing in the runtime could *say* how fast that is: cost
accounting (PR 6) records static FLOPs/bytes per executable but never
pairs them with measured wall time, and the static HBM estimator (PR 12)
predicts peaks nothing checks against reality.  The observatory closes
both loops, always on, at production overhead:

* **Execution ledger** — ``core/dispatch.py`` notes the monotonic wall
  time of every cached-executable call into a bounded per-key table.
  Plain timings measure the *enqueue* (jax dispatch is async); every Nth
  call per key (``HEAT_TPU_PERF_SYNC_EVERY``) is additionally
  ``block_until_ready``-fenced so the sample measures **device time**.
  The ledger joins each key's fenced time with its cost-accounting
  FLOPs/bytes to report achieved GFLOP/s, GB/s, arithmetic intensity
  and a compute-vs-bandwidth-bound verdict against the device peaks.
* **Device peaks** — ``HEAT_TPU_PEAK_FLOPS`` / ``HEAT_TPU_PEAK_GBPS``
  knobs (FLOP/s and bytes/s), with a one-shot matmul/copy
  micro-calibration fallback whose result can persist across processes
  (``HEAT_TPU_PEAK_CACHE``: atomic + CRC32 sidecar, invalidated on a
  jax/backend/device fingerprint change — the AOT-cache discipline).
* **Live HBM watermarks** — version-guarded ``device.memory_stats()``
  gauges (graceful host-RSS fallback on backends without them, e.g.
  CPU), continuously cross-checked against the static estimator's
  ``analysis.hbm_predicted_peak_bytes``: measured exceeding the armed
  ``HEAT_TPU_HBM_BUDGET_BYTES`` or the prediction by
  ``HEAT_TPU_HBM_ALERT_MARGIN`` fires the deduplicated ``hbm:watermark``
  alert — the runtime companion to the static J301 diagnostic.
* **On-demand profiler capture** — ``/profilez`` starts/stops a bounded
  ``jax.profiler`` trace (single in-flight, duration capped at
  ``HEAT_TPU_PROFILE_MAX_S``, artifacts listed and downloadable).

Surfaces: the ``/rooflinez`` route (HTML table + ``?format=json``),
``/statusz`` + crash flight-recorder bundles + the
``HEAT_TPU_METRICS_DUMP`` atexit JSON (all carry the ``observatory``
section, rendered by the inspect CLI), and the fleet router's
``/fleetz`` rollup (each replica's observatory snapshot merged into one
fleet-wide per-kernel utilization table with the slowest replica per
key highlighted).  See docs/observability.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis import tsan as _tsan
from . import alerts as _alerts
from . import metrics as _metrics

__all__ = [
    "armed",
    "capture_status",
    "device_peaks",
    "ledger_report",
    "note",
    "render_profilez_html",
    "render_rooflinez_html",
    "reset",
    "reset_peaks",
    "rooflinez_report",
    "set_enabled",
    "set_memory_stats_provider",
    "set_peaks",
    "set_sync_every",
    "snapshot",
    "start_capture",
    "stop_capture",
    "watermark",
    "watermark_tick",
]

# direct environ reads (every knob IS registered in core/_env.py KNOBS):
# this module is imported by core.dispatch, so importing core._env here
# would re-enter the core import chain — the flight_recorder pattern
_ENABLED = os.environ.get("HEAT_TPU_OBSERVATORY", "1").strip().lower() not in (
    "0", "false", "no", "off",
)
_SYNC_EVERY = int(os.environ.get("HEAT_TPU_PERF_SYNC_EVERY", "16") or "0")

_LEDGER_MAX = 1024
_CAPTURES_KEPT = 16
_WATERMARK_MIN_PERIOD_S = 0.5

#: ledger + calibration + watermark state: written by whichever thread
#: dispatches (fit thread, coalescer batcher), read by /rooflinez and
#: /statusz handler threads, the crash excepthook, and the atexit dump
_LEDGER_LOCK = _tsan.register_lock("telemetry.observatory")
#: profiler capture state: /profilez handler threads + the auto-stop timer
_PROF_LOCK = _tsan.register_lock("telemetry.observatory.profiler")


class _KeyStats:
    """Per-dispatch-key measurement accumulator (guarded by the ledger
    lock)."""

    __slots__ = ("calls", "total_s", "sync_samples", "sync_total_s", "sync_min_s")

    def __init__(self):
        self.calls = 0
        self.total_s = 0.0
        self.sync_samples = 0
        self.sync_total_s = 0.0
        self.sync_min_s = float("inf")


_LEDGER: "Dict[Any, _KeyStats]" = {}

#: calibrated/derived device peaks (FLOP/s, bytes/s) + provenance
_PEAKS: Optional[Dict[str, Any]] = None
#: single-flight guard: exactly one thread runs the calibration kernels;
#: concurrent /rooflinez scrapes degrade to peaks-unknown instead of
#: each launching their own matmul storm on a serving replica
_CALIBRATING = False

#: watermark bookkeeping: last sample + peak-seen + throttle stamp
_WM: Dict[str, Any] = {"last": None, "peak_seen": 0.0, "ts": 0.0}

#: test hook: () -> (bytes_in_use, peak_bytes, source) or None
_MEM_PROVIDER: Optional[Callable[[], Optional[Tuple[float, float, str]]]] = None

_SYNC_C = _metrics.counter(
    "observatory.sync_samples", "block_until_ready-fenced ledger samples"
)
_WM_CHECKS_C = _metrics.counter(
    "observatory.watermark_checks", "HBM watermark cross-checks run"
)
_HBM_ALERTS_C = _metrics.counter(
    "observatory.hbm_alerts", "measured-vs-predicted/budget HBM alert firings"
)
_CAPTURES_C = _metrics.counter(
    "observatory.profiler_captures", "jax.profiler captures completed via /profilez"
)
_metrics.gauge(
    "observatory.ledger_size", "dispatch keys currently tracked by the ledger",
    fn=lambda: len(_LEDGER),
)
_metrics.gauge(
    "observatory.hbm_bytes_in_use", "last sampled device/host memory in use",
    fn=lambda: float((_WM["last"] or {}).get("bytes_in_use", 0.0)),
)
_metrics.gauge(
    "observatory.hbm_peak_bytes", "highest watermark sampled this process",
    fn=lambda: float(_WM["peak_seen"]),
)
_PEAK_FLOPS_G = _metrics.gauge(
    "observatory.peak_flops", "device peak FLOP/s in effect (env or calibrated)"
)
_PEAK_GBPS_G = _metrics.gauge(
    "observatory.peak_bytes_per_s", "device peak bytes/s in effect (env or calibrated)"
)


def armed() -> bool:
    """Whether the execution ledger records dispatches (the one check
    ``core/dispatch.py`` pays per call when off)."""
    return _ENABLED


def set_enabled(enabled: bool) -> bool:
    """Arm/disarm the ledger at runtime (overrides the env knob);
    returns the previous state.  Bench/gate hook."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    return prev


def set_sync_every(n: int) -> int:
    """Set the fenced-sample period (0 = never fence); returns the
    previous period."""
    global _SYNC_EVERY
    prev = _SYNC_EVERY
    _SYNC_EVERY = max(0, int(n))
    return prev


def sync_every() -> int:
    return _SYNC_EVERY


def note(key, duration_s: float, out) -> None:
    """Record one cached-executable call (dispatch hot path).

    ``duration_s`` is the unfenced wall time of the call (enqueue on an
    async backend).  Every ``HEAT_TPU_PERF_SYNC_EVERY``-th call per key
    additionally fences on ``out`` so the sample measures device time —
    the fence runs OUTSIDE the ledger lock (a blocked dispatch must not
    block /rooflinez scrapes), and piggybacks a throttled HBM watermark
    cross-check (the "continuous" half of the measured-vs-predicted
    alert: it runs exactly when the device is provably done working)."""
    do_sync = False
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        st = _LEDGER.get(key)
        if st is None:
            if len(_LEDGER) >= _LEDGER_MAX:
                _LEDGER.clear()  # bounded like the dispatch _aval_cache
            st = _LEDGER[key] = _KeyStats()
        st.calls += 1
        st.total_s += duration_s
        if _SYNC_EVERY and st.calls % _SYNC_EVERY == 0:
            do_sync = True
            t0 = time.perf_counter()
    if not do_sync:
        return
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # lint: allow H501(non-blockable output; the unfenced sample stands)
        return
    dt = duration_s + (time.perf_counter() - t0)
    _SYNC_C.inc()
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        st = _LEDGER.get(key)
        if st is not None:
            st.sync_samples += 1
            st.sync_total_s += dt
            if dt < st.sync_min_s:
                st.sync_min_s = dt
    watermark_tick()


def reset() -> None:
    """Drop every ledger entry and the watermark peak (tests/bench)."""
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        _LEDGER.clear()
        _WM["last"] = None
        _WM["peak_seen"] = 0.0
        _WM["ts"] = 0.0


def reset_peaks() -> None:
    """Forget the resolved device peaks so the next
    :func:`device_peaks` re-resolves env/cache/calibration (tests)."""
    global _PEAKS
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        _PEAKS = None


# ----------------------------------------------------------------------
# device peaks: env knobs -> on-disk cache -> one-shot micro-calibration
# ----------------------------------------------------------------------
def _device_fingerprint() -> str:
    try:
        import jax

        devs = jax.devices()
        return (
            f"jax={jax.__version__}|backend={jax.default_backend()}"
            f"|kind={devs[0].device_kind if devs else '?'}|n={len(devs)}"
        )
    except Exception:  # lint: allow H501(no backend: fingerprint degrades, cache misses)
        return "no-backend"


def _calibrate() -> Dict[str, float]:
    """One-shot matmul/copy micro-calibration of the device peaks.

    The matmul is the canonical MXU/FMA-saturating kernel; the stream
    kernel reads + writes one f32 vector (8 bytes moved per element).
    Min over a few fenced repeats — calibration noise is one-sided."""
    import jax
    import jax.numpy as jnp

    n = 512
    a = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    jax.block_until_ready(mm(a))  # compile outside the sample
    t_mm = min(_timed(lambda: mm(a)) for _ in range(3))

    m = 1 << 22
    v = jnp.ones((m,), jnp.float32)
    st = jax.jit(lambda x: x * 1.000001 + 0.5)
    jax.block_until_ready(st(v))
    t_st = min(_timed(lambda: st(v)) for _ in range(3))

    return {
        "flops": 2.0 * n**3 / max(t_mm, 1e-9),
        "bytes_per_s": 8.0 * m / max(t_st, 1e-9),
    }


def _timed(fn) -> float:
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn())
    return time.perf_counter() - t0


def _peak_cache_path() -> str:
    return os.environ.get("HEAT_TPU_PEAK_CACHE", "")


def _load_peak_cache(path: str, fingerprint: str) -> Optional[Dict[str, float]]:
    """Checksum-verified calibration artifact, or None (missing, torn,
    or recorded under a different jax/backend/device fingerprint)."""
    try:
        from ..resilience.atomic import verify_checksum

        verify_checksum(path)
        with open(path) as f:
            doc = json.load(f)
        if doc.get("fingerprint") != fingerprint:
            return None
        return {"flops": float(doc["flops"]), "bytes_per_s": float(doc["bytes_per_s"])}
    except Exception:  # lint: allow H501(bad/missing cache artifact -> recalibrate, never crash)
        return None


def _save_peak_cache(path: str, fingerprint: str, peaks: Dict[str, float]) -> None:
    try:
        from ..resilience.atomic import atomic_write

        with atomic_write(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "fingerprint": fingerprint,
                        "flops": peaks["flops"],
                        "bytes_per_s": peaks["bytes_per_s"],
                        "calibrated_at": time.time(),
                    },
                    f,
                    indent=1,
                )
    except Exception:  # lint: allow H501(a read-only cache dir must not break calibration)
        pass


def set_peaks(flops: float, bytes_per_s: float, source: str = "manual") -> None:
    """Install explicit device peaks (tests, operators with spec sheets)."""
    global _PEAKS
    doc = {
        "flops": float(flops),
        "bytes_per_s": float(bytes_per_s),
        "source": source,
        "fingerprint": _device_fingerprint(),
    }
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        _PEAKS = doc
    _PEAK_FLOPS_G.set(doc["flops"])
    _PEAK_GBPS_G.set(doc["bytes_per_s"])


def device_peaks(calibrate: bool = True) -> Optional[Dict[str, Any]]:
    """The device peaks in effect: ``{"flops", "bytes_per_s", "source",
    "fingerprint"}`` (FLOP/s and bytes/s).

    Resolution order: the already-resolved value, the
    ``HEAT_TPU_PEAK_FLOPS``/``HEAT_TPU_PEAK_GBPS`` knobs (FLOP/s and
    GB/s respectively, both must be set), a fingerprint-matched
    ``HEAT_TPU_PEAK_CACHE`` artifact, then — only when
    ``calibrate=True`` — the one-shot micro-calibration (persisted back
    to the cache path when configured).  ``calibrate=False`` (the
    /statusz embed, crash bundles) never runs device work and returns
    None when no cheap source resolves."""
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger", write=False)
        if _PEAKS is not None:
            return dict(_PEAKS)
    try:
        env_flops = float(os.environ.get("HEAT_TPU_PEAK_FLOPS", "0") or 0.0)
        env_gbps = float(os.environ.get("HEAT_TPU_PEAK_GBPS", "0") or 0.0)
    except ValueError:
        env_flops = env_gbps = 0.0
    if env_flops > 0.0 and env_gbps > 0.0:
        set_peaks(env_flops, env_gbps * 1e9, source="env")
        return device_peaks(calibrate=False)
    fingerprint = _device_fingerprint()
    cache = _peak_cache_path()
    if cache:
        cached = _load_peak_cache(cache, fingerprint)
        if cached is not None:
            set_peaks(cached["flops"], cached["bytes_per_s"], source="cache")
            return device_peaks(calibrate=False)
    if not calibrate:
        return None
    global _CALIBRATING
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        if _CALIBRATING:
            # another thread is already running the calibration kernels;
            # this caller reports peaks-unknown rather than doubling the
            # device work (the kernels run OUTSIDE the lock, so waiting
            # here would stall /rooflinez scrapes behind device time)
            return None
        _CALIBRATING = True
    try:
        peaks = _calibrate()
    except Exception:  # lint: allow H501(no usable backend: roofline reports peaks unknown)
        return None
    finally:
        with _LEDGER_LOCK:
            _tsan.note_access("telemetry.observatory.ledger")
            _CALIBRATING = False
    set_peaks(peaks["flops"], peaks["bytes_per_s"], source="calibrated")
    if cache:
        _save_peak_cache(cache, fingerprint, peaks)
    return device_peaks(calibrate=False)


# ----------------------------------------------------------------------
# HBM watermarks + the measured-vs-predicted cross-check
# ----------------------------------------------------------------------
def set_memory_stats_provider(provider) -> None:
    """Install a memory-stats source for tests: ``() ->
    (bytes_in_use, peak_bytes, source)`` or None; pass ``None`` to
    restore the device/host probe."""
    global _MEM_PROVIDER
    _MEM_PROVIDER = provider


def _probe_memory() -> Optional[Tuple[float, float, str]]:
    """(bytes_in_use, peak_bytes, source) from the best available
    source: per-device ``memory_stats()`` summed over local devices
    (version-guarded — absent fields degrade to 0), else the host RSS
    (a CPU backend's "device memory" IS host memory), else None."""
    if _MEM_PROVIDER is not None:
        return _MEM_PROVIDER()
    try:
        import jax

        in_use = peak = 0.0
        found = False
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not isinstance(stats, dict):
                continue
            found = True
            in_use += float(stats.get("bytes_in_use", 0) or 0)
            peak += float(
                stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)) or 0
            )
        if found:
            return in_use, peak, "device"
    except Exception:  # lint: allow H501(no backend yet; fall through to the host probe)
        pass
    try:
        import resource

        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        in_use = float(rss_pages * os.sysconf("SC_PAGE_SIZE"))
        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
        return in_use, peak, "host_rss"
    except Exception:  # lint: allow H501(non-linux host: watermarks report nothing)
        return None


def watermark() -> Dict[str, Any]:
    """The last watermark sample (sampling one fresh if none yet)."""
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger", write=False)
        last = _WM["last"]
    if last is None:
        watermark_tick(force=True)
        with _LEDGER_LOCK:
            _tsan.note_access("telemetry.observatory.ledger", write=False)
            last = _WM["last"]
    return dict(last or {"source": None})


def _predicted_peak_bytes() -> float:
    """The static estimator's worst recorded per-device peak (lazy: the
    analysis layer imports jax + core)."""
    try:
        from ..analysis import memory_model as _mm

        return float(_mm.predicted_peak_bytes())
    except Exception:  # lint: allow H501(analysis layer unavailable: no prediction to check)
        return 0.0


def _hbm_budget_bytes() -> float:
    try:
        return float(os.environ.get("HEAT_TPU_HBM_BUDGET_BYTES", "0") or 0)
    except ValueError:
        return 0.0


def watermark_tick(force: bool = False) -> Optional[Dict[str, Any]]:
    """One watermark sample + cross-check (throttled to one per
    ``_WATERMARK_MIN_PERIOD_S`` unless forced).

    With ``HEAT_TPU_HBM_BUDGET_BYTES`` armed (> 0), fires the
    ``hbm:watermark`` alert when the measured in-use bytes exceed the
    budget (cause ``budget`` — the runtime companion to the static J301
    verdict) or the static estimator's predicted per-device peak by
    ``HEAT_TPU_HBM_ALERT_MARGIN`` (cause ``predicted`` — the prediction
    was wrong, trust it less); resolves the alert when the measurement
    drops back under.  Unarmed (budget 0, the default) the sample is
    recorded but no verdicts fire: a process-wide in-use number always
    dwarfs any single program's predicted peak, so the predicted
    cross-check is only meaningful against an operator-stated budget
    ceiling.  Returns the sample doc, or None when throttled / no
    memory source exists."""
    now = time.monotonic()
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger", write=False)
        if not force and now - _WM["ts"] < _WATERMARK_MIN_PERIOD_S:
            return None
    probe = _probe_memory()
    if probe is None:
        return None
    in_use, peak, source = probe
    _WM_CHECKS_C.inc()
    predicted = _predicted_peak_bytes()
    budget = _hbm_budget_bytes()
    try:
        margin = float(os.environ.get("HEAT_TPU_HBM_ALERT_MARGIN", "1.25") or 1.25)
    except ValueError:
        margin = 1.25
    doc = {
        "bytes_in_use": in_use,
        "peak_bytes": peak,
        "source": source,
        "predicted_peak_bytes": predicted,
        "budget_bytes": budget,
        "margin": margin,
        "sampled_at": time.time(),
    }
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger")
        _WM["last"] = doc
        _WM["ts"] = now
        if in_use > _WM["peak_seen"]:
            _WM["peak_seen"] = in_use
    armed_check = budget > 0
    over_budget = armed_check and in_use > budget
    over_predicted = armed_check and predicted > 0 and in_use > predicted * margin
    for cause, over, bound in (
        ("budget", over_budget, budget),
        ("predicted", over_predicted, predicted * margin),
    ):
        if over:
            if _alerts.fire(
                "hbm:watermark",
                severity="page",
                message=(
                    f"measured memory in use {in_use:,.0f} B ({source}) exceeds the "
                    + (
                        f"armed HBM budget {budget:,.0f} B"
                        if cause == "budget"
                        else f"statically predicted peak {predicted:,.0f} B x "
                        f"margin {margin:g}"
                    )
                    + " — the runtime companion to J301"
                ),
                value=in_use,
                threshold=bound,
                labels={"cause": cause},
            ):
                _HBM_ALERTS_C.inc()
        else:
            _alerts.resolve("hbm:watermark", labels={"cause": cause})
    return doc


# ----------------------------------------------------------------------
# the roofline join
# ----------------------------------------------------------------------
def _ledger_rows() -> List[Tuple[str, Dict[str, Any]]]:
    """(key_repr, raw timing doc) per tracked key, under one lock hold."""
    from ..core import dispatch as _dispatch

    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger", write=False)
        items = [(k, (s.calls, s.total_s, s.sync_samples, s.sync_total_s, s.sync_min_s))
                 for k, s in _LEDGER.items()]
    rows = []
    for key, (calls, total_s, n_sync, sync_total_s, sync_min_s) in items:
        fenced = n_sync > 0
        mean_s = (sync_total_s / n_sync) if fenced else (total_s / calls if calls else 0.0)
        rows.append(
            (
                _dispatch._key_repr(key),
                {
                    "calls": calls,
                    "total_ms": round(total_s * 1e3, 6),
                    "mean_ms": round(mean_s * 1e3, 6),
                    "enqueue_mean_ms": round(total_s / calls * 1e3, 6) if calls else 0.0,
                    "sync_samples": n_sync,
                    "sync_min_ms": round(sync_min_s * 1e3, 6) if fenced else None,
                    "timing": "fenced" if fenced else "enqueue",
                    "_mean_s": mean_s,
                },
            )
        )
    return rows


def _sig(x: float) -> float:
    """4 significant digits: a 231-FLOP bucket program's 2.3e-4 GFLOP/s
    must not round to a falsy 0.0 the way fixed decimals would."""
    return float(f"{x:.4g}")


def ledger_report(peaks: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
    """Per-executable roofline rows, slowest total time first.

    Each row joins the ledger's measured time with the key's
    cost-accounting record (when one exists): achieved GFLOP/s and GB/s
    from the fenced mean, arithmetic intensity (FLOPs/byte), the
    roofline ceiling at that intensity, the utilization against it, and
    the bound-class verdict (``compute``/``bandwidth``; ``unknown``
    without peaks or cost data)."""
    from ..core import dispatch as _dispatch

    per_key_cost = _dispatch.cost_summary()["per_key"]
    peak_flops = float(peaks["flops"]) if peaks else 0.0
    peak_bps = float(peaks["bytes_per_s"]) if peaks else 0.0
    out = []
    for key_repr, doc in _ledger_rows():
        mean_s = doc.pop("_mean_s")
        cost = per_key_cost.get(key_repr)
        row = dict(doc, key=key_repr, flops=None, bytes=None,
                   gflops_per_s=None, gbytes_per_s=None, intensity=None,
                   utilization=None, bound="unknown")
        if cost and mean_s > 0:
            flops = float(cost.get("flops", 0.0) or 0.0)
            nbytes = float(cost.get("bytes_accessed", 0.0) or 0.0)
            row["flops"] = flops
            row["bytes"] = nbytes
            if flops > 0:
                row["gflops_per_s"] = _sig(flops / mean_s / 1e9)
            if nbytes > 0:
                row["gbytes_per_s"] = _sig(nbytes / mean_s / 1e9)
                if flops > 0:
                    row["intensity"] = _sig(flops / nbytes)
            if peak_flops > 0 and peak_bps > 0 and nbytes > 0:
                if flops > 0:
                    intensity = flops / nbytes
                    ridge = peak_flops / peak_bps
                    roof = min(peak_flops, intensity * peak_bps)
                    row["bound"] = "compute" if intensity >= ridge else "bandwidth"
                    row["utilization"] = _sig(flops / mean_s / roof)
                else:
                    row["bound"] = "bandwidth"
                    row["utilization"] = _sig(nbytes / mean_s / peak_bps)
        out.append(row)
    out.sort(key=lambda r: r["total_ms"], reverse=True)
    return out


def snapshot(calibrate: bool = False, max_rows: int = 50) -> Dict[str, Any]:
    """The ``observatory`` section /statusz, crash bundles and the
    metrics-dump atexit JSON embed: ledger rows (capped), the last
    watermark sample, and the calibration provenance.  Never runs
    device work unless ``calibrate=True``."""
    peaks = device_peaks(calibrate=calibrate)
    with _LEDGER_LOCK:
        _tsan.note_access("telemetry.observatory.ledger", write=False)
        wm = dict(_WM["last"] or {})
        peak_seen = _WM["peak_seen"]
    return {
        "enabled": _ENABLED,
        "sync_every": _SYNC_EVERY,
        "peaks": peaks,
        "watermark": dict(wm, peak_seen_bytes=peak_seen) if wm else None,
        "ledger": ledger_report(peaks)[:max_rows],
    }


def rooflinez_report(calibrate: bool = True, limit: Optional[int] = None) -> Dict[str, Any]:
    """The machine form of ``/rooflinez`` (``?format=json``).

    ``limit`` caps the ledger rows (slowest first) — the fleet router's
    health poller scrapes with a limit so a replica with a thousand
    tracked keys cannot bloat every poll."""
    peaks = device_peaks(calibrate=calibrate)
    ledger = ledger_report(peaks)
    truncated = limit is not None and len(ledger) > int(limit)
    return {
        "timestamp": time.time(),
        "pid": os.getpid(),
        "enabled": _ENABLED,
        "sync_every": _SYNC_EVERY,
        "peaks": peaks,
        "watermark": watermark(),
        "ledger": ledger[: int(limit)] if limit is not None else ledger,
        "ledger_total": len(ledger),
        "truncated": truncated,
        "profiler": capture_status(),
    }


def render_rooflinez_html() -> str:
    """The human form of ``/rooflinez``: peaks + watermark header and
    the per-executable roofline table."""
    import html as _html

    doc = rooflinez_report()
    peaks = doc["peaks"]
    wm = doc["watermark"] or {}
    head = "<h1>/rooflinez — kernel roofline observatory</h1>"
    if peaks:
        head += (
            f"<p>device peaks ({_html.escape(str(peaks['source']))}): "
            f"{peaks['flops'] / 1e9:.1f} GFLOP/s · "
            f"{peaks['bytes_per_s'] / 1e9:.1f} GB/s · ridge "
            f"{peaks['flops'] / max(peaks['bytes_per_s'], 1e-9):.2f} FLOP/B</p>"
        )
    else:
        head += "<p>device peaks: unknown (set HEAT_TPU_PEAK_FLOPS/GBPS or allow calibration)</p>"
    if wm.get("source"):
        head += (
            f"<p>memory watermark ({_html.escape(str(wm['source']))}): "
            f"{wm.get('bytes_in_use', 0) / 2**20:.1f} MiB in use · "
            f"predicted peak {wm.get('predicted_peak_bytes', 0) / 2**20:.1f} MiB · "
            f"budget {wm.get('budget_bytes', 0) / 2**20:.1f} MiB</p>"
        )
    cols = (
        "executable", "calls", "mean ms", "timing", "GFLOP/s", "GB/s",
        "intensity", "util", "bound",
    )
    rows = []
    for r in doc["ledger"]:
        rows.append(
            "<tr>"
            + "".join(
                f"<td>{_html.escape(str(v)) if v is not None else '—'}</td>"
                for v in (
                    r["key"], r["calls"], r["mean_ms"], r["timing"],
                    r["gflops_per_s"], r["gbytes_per_s"], r["intensity"],
                    r["utilization"], r["bound"],
                )
            )
            + "</tr>"
        )
    table = (
        "<table border=1 cellpadding=3><tr>"
        + "".join(f"<th>{c}</th>" for c in cols)
        + "</tr>"
        + "".join(rows)
        + "</table>"
    )
    if not rows:
        table = "<p>no dispatches recorded yet</p>"
    prof = doc["profiler"]
    prof_html = (
        f"<p>profiler: {'capture in flight' if prof['active'] else 'idle'} · "
        f"{len(prof['captures'])} completed capture(s) — POST /profilez/start "
        "to begin one (see /profilez)</p>"
    )
    return (
        "<html><head><title>/rooflinez</title></head><body>"
        + head + table + prof_html + "</body></html>"
    )


# ----------------------------------------------------------------------
# on-demand bounded profiler capture (/profilez)
# ----------------------------------------------------------------------
_PROF: Dict[str, Any] = {
    "active": False,
    "dir": None,
    "started_ts": 0.0,
    "duration_s": 0.0,
    "timer": None,
    "base_dir": None,
    "seq": 0,
    "captures": [],  # bounded history of completed captures
}


def _profile_base_dir() -> str:
    base = os.environ.get("HEAT_TPU_PROFILE_DIR", "")
    if not base:
        import tempfile

        base = os.path.join(tempfile.gettempdir(), f"heat_tpu_profilez_{os.getpid()}")
    os.makedirs(base, exist_ok=True)
    return base


def _profile_max_s() -> float:
    try:
        return max(0.1, float(os.environ.get("HEAT_TPU_PROFILE_MAX_S", "30") or 30))
    except ValueError:
        return 30.0


def start_capture(duration_s: Optional[float] = None) -> Dict[str, Any]:
    """Start one bounded ``jax.profiler`` capture.

    Single in-flight: a second start while one runs raises
    ``RuntimeError`` (the /profilez route maps it to 409).  The duration
    is capped at ``HEAT_TPU_PROFILE_MAX_S``; an auto-stop timer fires at
    the deadline so a forgotten capture can never trace forever."""
    cap = _profile_max_s()
    duration = cap if duration_s is None else max(0.05, min(float(duration_s), cap))
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler")
        if _PROF["active"]:
            raise RuntimeError(
                f"a profiler capture is already in flight (dir {_PROF['dir']!r}); "
                "stop it first (POST /profilez/stop)"
            )
        if _PROF["base_dir"] is None:
            _PROF["base_dir"] = _profile_base_dir()
        _PROF["seq"] += 1
        cap_dir = os.path.join(_PROF["base_dir"], f"capture_{_PROF['seq']:03d}")
        _PROF["active"] = True
        _PROF["dir"] = cap_dir
        _PROF["started_ts"] = time.time()
        _PROF["duration_s"] = duration
    try:
        import jax

        os.makedirs(cap_dir, exist_ok=True)
        jax.profiler.start_trace(cap_dir)
    except Exception as e:  # lint: allow H501(profiler unavailable: release the slot and report)
        with _PROF_LOCK:
            _tsan.note_access("telemetry.observatory.profiler")
            _PROF["active"] = False
            _PROF["dir"] = None
        raise RuntimeError(f"jax.profiler.start_trace failed: {e}") from None
    timer = threading.Timer(duration, _auto_stop, args=(cap_dir,))
    timer.daemon = True
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler")
        _PROF["timer"] = timer
    timer.start()
    return {"dir": cap_dir, "duration_s": duration, "started_ts": _PROF["started_ts"]}


def _auto_stop(cap_dir: str) -> None:
    """Deadline auto-stop (only if the same capture is still active)."""
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler", write=False)
        if not (_PROF["active"] and _PROF["dir"] == cap_dir):
            return
    try:
        stop_capture(reason="deadline")
    except Exception:  # lint: allow H501(racing a manual stop is fine; exactly one wins)
        pass


def _artifact_list(cap_dir: str) -> List[Dict[str, Any]]:
    files = []
    for root, _dirs, names in os.walk(cap_dir):
        for name in sorted(names):
            p = os.path.join(root, name)
            try:
                files.append(
                    {
                        "name": os.path.relpath(p, _PROF["base_dir"] or cap_dir),
                        "bytes": os.path.getsize(p),
                    }
                )
            except OSError:
                continue
    return files


def stop_capture(reason: str = "manual") -> Dict[str, Any]:
    """Stop the in-flight capture; returns its record (dir + artifact
    list).  Raises ``RuntimeError`` when none is running."""
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler")
        if not _PROF["active"]:
            raise RuntimeError("no profiler capture in flight")
        cap_dir = _PROF["dir"]
        timer = _PROF["timer"]
        _PROF["active"] = False
        _PROF["dir"] = None
        _PROF["timer"] = None
    if timer is not None:
        timer.cancel()
    err = None
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # lint: allow H501(stop after a failed start must still free the slot)
        err = f"{type(e).__name__}: {e}"
    rec = {
        "dir": cap_dir,
        "stopped_ts": time.time(),
        "reason": reason,
        "artifacts": _artifact_list(cap_dir),
        "error": err,
    }
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler")
        _PROF["captures"].append(rec)
        del _PROF["captures"][:-_CAPTURES_KEPT]
    _CAPTURES_C.inc()
    return rec


def capture_status() -> Dict[str, Any]:
    """The /profilez status doc: in-flight state + completed captures."""
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler", write=False)
        return {
            "active": _PROF["active"],
            "dir": _PROF["dir"],
            "started_ts": _PROF["started_ts"] if _PROF["active"] else None,
            "duration_s": _PROF["duration_s"] if _PROF["active"] else None,
            "max_duration_s": _profile_max_s(),
            "captures": [dict(c) for c in _PROF["captures"]],
        }


def artifact_path(name: str) -> str:
    """Absolute path of a capture artifact by its listed relative name;
    refuses anything escaping the capture base directory (the /profilez
    download route's traversal guard)."""
    with _PROF_LOCK:
        _tsan.note_access("telemetry.observatory.profiler", write=False)
        base = _PROF["base_dir"]
    if not base:
        raise FileNotFoundError("no captures have been taken")
    base_real = os.path.realpath(base)
    p = os.path.realpath(os.path.join(base_real, name))
    if not (p == base_real or p.startswith(base_real + os.sep)):
        raise PermissionError(f"artifact {name!r} escapes the capture directory")
    if not os.path.isfile(p):
        raise FileNotFoundError(f"no capture artifact {name!r}")
    return p


def render_profilez_html() -> str:
    """The human form of ``/profilez``."""
    import html as _html

    doc = capture_status()
    lines = ["<html><head><title>/profilez</title></head><body>",
             "<h1>/profilez — on-demand profiler capture</h1>"]
    if doc["active"]:
        lines.append(
            f"<p>capture IN FLIGHT in {_html.escape(str(doc['dir']))} "
            f"(auto-stops after {doc['duration_s']:g}s) — "
            "POST /profilez/stop to finish early</p>"
        )
    else:
        lines.append(
            "<p>idle — <code>curl -X POST "
            f"'http://.../profilez/start?duration_s=5'</code> begins a capture "
            f"(cap {doc['max_duration_s']:g}s)</p>"
        )
    for c in doc["captures"]:
        lines.append(
            f"<h3>{_html.escape(str(c['dir']))} ({_html.escape(str(c['reason']))})</h3><ul>"
        )
        for a in c["artifacts"]:
            name = _html.escape(str(a["name"]))
            lines.append(
                f"<li><a href=\"/profilez/artifact?name={name}\">{name}</a> "
                f"({a['bytes']} B)</li>"
            )
        lines.append("</ul>")
    lines.append("</body></html>")
    return "".join(lines)


# the observatory section rides in the HEAT_TPU_METRICS_DUMP atexit JSON
# (and crash bundles / statusz add it explicitly)
_metrics.register_dump_section("observatory", lambda: snapshot(calibrate=False))
