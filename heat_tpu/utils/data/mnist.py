"""MNIST dataset split across the mesh, analog of heat/utils/data/mnist.py.

The reference subclasses torchvision's MNIST and slices the raw tensors
per rank.  torchvision may be absent here; when it is, a synthetic
MNIST-shaped dataset generator is provided so the DP training example and
benchmarks run hermetically.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core.dndarray import DNDarray
from .datatools import Dataset

__all__ = ["MNISTDataset", "synthetic_mnist"]

try:  # pragma: no cover - optional dependency
    from torchvision import datasets as _tv_datasets

    _TORCHVISION = True
except Exception:  # lint: allow H501(optional torchvision import guard)
    _TORCHVISION = False


def synthetic_mnist(n: int = 1024, seed: int = 0) -> Tuple[DNDarray, DNDarray]:
    """Deterministic MNIST-shaped synthetic digits (28x28 images, 10
    classes) for hermetic benchmarks."""
    from ...core import factories

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    base = rng.standard_normal((10, 28, 28)).astype(np.float32)
    imgs = base[labels] + 0.3 * rng.standard_normal((n, 28, 28)).astype(np.float32)
    return factories.array(imgs[..., None], split=0), factories.array(labels, split=0)


class MNISTDataset(Dataset):
    """MNIST over the mesh (mnist.py:15)."""

    def __init__(self, root: str, train: bool = True, transform=None, ishuffle: bool = False, test_set: bool = False, download: bool = True):
        from ...core import factories

        if _TORCHVISION:  # pragma: no cover - depends on torchvision presence
            tv = _tv_datasets.MNIST(root, train=train and not test_set, download=download)
            imgs = np.asarray(tv.data, dtype=np.float32)[..., None] / 255.0
            labels = np.asarray(tv.targets, dtype=np.int32)
            x = factories.array(imgs, split=0)
            y = factories.array(labels, split=0)
        else:
            x, y = synthetic_mnist()
        super().__init__([x, y], transforms=[transform, None], ishuffle=ishuffle)
        self.train = train

    @property
    def images(self) -> DNDarray:
        return self.arrays[0]

    @property
    def labels(self) -> DNDarray:
        return self.arrays[1]
