"""KMeans clustering, analog of heat/cluster/kmeans.py (kmeans.py:14).

The centroid update — a one-hot masked matmul + sum in the reference,
followed by an Allreduce across the sample-split axis — is a single
segment-sum expression on the sharded global array; XLA emits the psum.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import dispatch, kernels, types
from ..core.dndarray import DNDarray
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMeans"]


@partial(jax.jit, static_argnames=("n_true", "k"))
def _lloyd_update(xp: jax.Array, centers: jax.Array, n_true: int, k: int):
    """Trimmed Lloyd iteration: centroid update + shift ONLY.

    Measured on v5e: materializing labels/inertia/|x|^2 inside the
    iteration costs ~6x (extra HBM passes); the fit loop needs none of
    them until convergence, so the hot step computes exactly two passes
    over x (distance matmul, one-hot sums matmul) and two (N, k)
    intermediates.  Labels and inertia come from one final `_lloyd_step`.
    """
    return _lloyd_body(xp, centers, n_true, k)


@partial(jax.jit, static_argnames=("n_true", "k", "max_iter", "tol"))
def _lloyd_loop(xp: jax.Array, centers: jax.Array, n_true: int, k: int, max_iter: int, tol: float):
    """The whole Lloyd fit loop as one on-device ``lax.while_loop``.

    A Python loop checking ``float(shift) <= tol`` costs one device->host
    round trip per iteration (a full link RTT on a tunneled chip); here
    the convergence test runs on-device and the host syncs exactly once,
    after the loop.  Returns (centers, n_iter, last_shift).
    """

    def cond(carry):
        c, i, shift = carry
        return jnp.logical_and(i < max_iter, shift > tol)

    def body(carry):
        c, i, _ = carry
        new, shift = _lloyd_body(xp, c, n_true, k)
        return new, i + 1, shift

    init = (centers, jnp.int32(0), jnp.asarray(jnp.inf, jnp.float32))
    c, i, shift = jax.lax.while_loop(cond, body, init)
    return c, i, shift


def _lloyd_body(xp, centers, n_true, k):
    xc = xp @ centers.T
    c2 = jnp.sum(centers * centers, axis=1)
    labels = jnp.argmin(c2[None, :] - 2.0 * xc, axis=1)
    valid = jax.lax.broadcasted_iota(jnp.int32, (xp.shape[0],), 0) < n_true
    oh = jax.nn.one_hot(labels, k, dtype=xp.dtype) * valid.astype(xp.dtype)[:, None]
    sums = oh.T @ xp
    counts = jnp.sum(oh, axis=0)
    new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)
    shift = jnp.sum((new - centers) ** 2).astype(jnp.float32)
    return new, shift


@partial(jax.jit, static_argnames=("n_true", "k"))
def _lloyd_step(xp: jax.Array, centers: jax.Array, n_true: int, k: int):
    """One fused Lloyd iteration on the padded sharded array.

    The reference runs this as three separate distributed ops (ring cdist
    distance.py:209, argmin with a custom MPI op statistics.py:1372, one-hot
    matmul + Allreduce kmeans.py:80-120).  Fusing into one jitted program
    keeps the whole iteration on-device: assignment needs only
    ``|c|^2 - 2 x@c.T`` (the ``|x|^2`` row term cannot change the argmin),
    both matmuls ride the MXU, and under a sharded ``xp`` GSPMD turns the
    segment sums into a single psum over the sample axis.

    Returns (labels_padded, new_centers, shift, inertia).
    """
    xc = xp @ centers.T  # (N, k) — MXU
    c2 = jnp.sum(centers * centers, axis=1)
    half_d2 = c2[None, :] - 2.0 * xc  # squared distance minus |x|^2 row term
    labels = jnp.argmin(half_d2, axis=1)
    valid = jax.lax.broadcasted_iota(jnp.int32, (xp.shape[0],), 0) < n_true
    w = valid.astype(xp.dtype)
    oh = jax.nn.one_hot(labels, k, dtype=xp.dtype) * w[:, None]
    sums = oh.T @ xp  # (k, f) — MXU; GSPMD: psum across shards
    counts = jnp.sum(oh, axis=0)
    new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)
    shift = jnp.sum((new - centers) ** 2)
    x2 = jnp.sum(xp * xp, axis=1)
    inertia = jnp.sum(w * (x2 + jnp.min(half_d2, axis=1)))
    return labels, new, shift, inertia


class KMeans(_KCluster):
    """K-Means with Lloyd iterations (kmeans.py:14)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.cdist(x, y, quadratic_expansion=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
            checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            resume_from=resume_from,
        )

    def _account_lloyd_psum(self, x: DNDarray, xp):
        """Telemetry model of the GSPMD psum behind one launched Lloyd
        program: the per-cluster partial sums (k, f) plus counts (k,)
        reduced across the sample-split shards (`sums = oh.T @ xp` —
        XLA inserts the collective; this layer never issues it, so the
        comm accounting happens here at launch).  Returns a ``comm.psum``
        span to wrap the launch with; a no-op for replicated input."""
        if x.split is None or x.comm.size <= 1:
            return contextlib.nullcontext()
        k = self.n_clusters
        nbytes = (k * int(xp.shape[1]) + k) * xp.dtype.itemsize
        return x.comm.account_implicit("psum", nbytes, site="kmeans.lloyd")

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """New centers = per-cluster mean (kmeans.py:80-120)."""
        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        labels = matching_centroids._dense()
        k = self.n_clusters
        sums = jax.ops.segment_sum(dense, labels, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((dense.shape[0],), dense.dtype), labels, num_segments=k)
        old = self._cluster_centers._dense()
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), old)
        return DNDarray.from_dense(new, None, x.device, x.comm)

    def _fused_step(self, x: DNDarray):
        """Run one fused Lloyd iteration; returns the center shift and
        updates ``self._cluster_centers``.

        Default path is the trimmed XLA program (`_lloyd_update`); the
        single-HBM-pass Pallas kernel (core/kernels.py) is opt-in via
        HEAT_TPU_LLOYD_KERNEL=1 — on v5e it measures VPU-bound and loses
        to XLA's multi-pass (see kernels.py for the numbers).  Labels are
        deliberately not produced — the fit loop only needs them once,
        after convergence (``_assign_padded``).
        """
        xp = x.larray_padded
        if not types.heat_type_is_inexact(x.dtype):
            xp = xp.astype(jnp.float32)
        centers = self._cluster_centers._dense().astype(xp.dtype)
        dispatch.record_external_dispatch()  # one launch per Lloyd step
        with self._account_lloyd_psum(x, xp):
            if kernels.LLOYD_KERNEL and kernels.lloyd_supported(xp.shape[1], self.n_clusters):
                new, shift, _ = kernels.lloyd_update(x, centers)
            else:
                new, shift = _lloyd_update(xp, centers, x.shape[0], self.n_clusters)
        self._cluster_centers = DNDarray.from_dense(new, None, x.device, x.comm)
        return shift

    def _assign_padded(self, x: DNDarray):
        """Labels + inertia against the current centers (one cheap pass)."""
        xp = x.larray_padded
        if not types.heat_type_is_inexact(x.dtype):
            xp = xp.astype(jnp.float32)
        centers = self._cluster_centers._dense().astype(xp.dtype)
        dispatch.record_external_dispatch()
        with self._account_lloyd_psum(x, xp):
            labels, _, _, inertia = _lloyd_step(xp, centers, x.shape[0], self.n_clusters)
        return labels, inertia

    def fit(self, x: DNDarray) -> "KMeans":
        """Lloyd iterations until center shift < tol (kmeans.py:~100)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        xp = x.larray_padded
        if not types.heat_type_is_inexact(x.dtype):
            xp = xp.astype(jnp.float32)
        if self._resumable:
            # chunked checkpoint/resume path: the SAME `_lloyd_body`
            # iteration sequence as the fast path, run checkpoint_every
            # iterations per device program, centers checkpointed (and
            # divergence-guarded) between chunks.  A killed fit resumed
            # from its last checkpoint reproduces the uninterrupted
            # result exactly.
            dtype = xp.dtype

            def run_chunk(centers, n):
                dispatch.record_external_dispatch()
                with self._account_lloyd_psum(x, xp):
                    return _lloyd_loop(
                        xp, jnp.asarray(centers, dtype), x.shape[0],
                        self.n_clusters, n, float(self.tol),
                    )

            def init_centers():
                self._initialize_cluster_centers(x)
                return self._cluster_centers._dense().astype(dtype)

            centers, n_iter = self._run_resumable(run_chunk, init_centers, "kmeans.iter")
            self._cluster_centers = DNDarray.from_dense(
                jnp.asarray(centers, dtype), None, x.device, x.comm
            )
            self._n_iter = n_iter
            labels, inertia = self._assign_padded(x)
            self._inertia = inertia
            self._labels = DNDarray.from_dense(labels[: x.shape[0]], x.split, x.device, x.comm)
            return self
        self._initialize_cluster_centers(x)
        centers = self._cluster_centers._dense().astype(xp.dtype)
        use_kernel = kernels.LLOYD_KERNEL and kernels.lloyd_supported(xp.shape[1], self.n_clusters)
        if use_kernel:
            # the opt-in Pallas path iterates from the host (one sync/iter)
            for i in range(self.max_iter):
                shift = self._fused_step(x)
                if float(shift) <= self.tol:
                    break
            n_iter = i + 1
        else:
            # whole fit loop on-device, and the iteration count stays a
            # device scalar — fit() performs ZERO host syncs; n_iter_ and
            # inertia_ convert lazily on first access (one link RTT each
            # on a tunneled chip, paid only if the caller looks).  ONE
            # dispatch for the whole fit, however many Lloyd iterations —
            # the dispatch-amortization invariant the micro-test pins.
            dispatch.record_external_dispatch()
            with self._account_lloyd_psum(x, xp):
                new, n_iter_dev, _ = _lloyd_loop(
                    xp, centers, x.shape[0], self.n_clusters, self.max_iter, float(self.tol)
                )
            self._cluster_centers = DNDarray.from_dense(new, None, x.device, x.comm)
            n_iter = n_iter_dev

        self._n_iter = n_iter
        # final assignment against the converged centers (the reference's
        # last pass only assigns, it does not move centers)
        labels, inertia = self._assign_padded(x)
        self._inertia = inertia
        self._labels = DNDarray.from_dense(labels[: x.shape[0]], x.split, x.device, x.comm)
        return self
