"""Linalg width (heat/core/linalg tests family): norm-order grid,
einsum expression grid across splits, vdot/inner/outer/kron edges, and
matrix_power negative exponents — numpy ground truth on the mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.fixture(scope="module")
def m():
    return np.random.default_rng(0).standard_normal((9, 6))


@pytest.fixture(scope="module")
def v():
    return np.random.default_rng(1).standard_normal(24)


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("ord_", ["fro", "nuc", 1, -1, 2, -2, np.inf, -np.inf])
def test_matrix_norm_orders(m, split, ord_):
    x = ht.array(m, split=split)
    np.testing.assert_allclose(
        float(ht.linalg.norm(x, ord=ord_)), np.linalg.norm(m, ord=ord_), rtol=1e-8
    )


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("ord_", [None, 1, 2, 3, np.inf, -np.inf, 0])
def test_vector_norm_orders(v, split, ord_):
    x = ht.array(v, split=split)
    np.testing.assert_allclose(
        float(ht.linalg.norm(x, ord=ord_)), np.linalg.norm(v, ord=ord_), rtol=1e-10
    )


@pytest.mark.parametrize("split", [None, 0])
def test_norm_axis_keepdims(m, split):
    x = ht.array(m, split=split)
    np.testing.assert_allclose(
        ht.linalg.norm(x, axis=1).numpy(), np.linalg.norm(m, axis=1), rtol=1e-10
    )
    got = ht.linalg.norm(x, axis=0, keepdims=True)
    assert got.shape == (1, 6)
    np.testing.assert_allclose(
        got.numpy(), np.linalg.norm(m, axis=0, keepdims=True), rtol=1e-10
    )


EINSUM_CASES = [
    ("ij->ji", 1),
    ("ij->i", 1),
    ("ij->", 1),
    ("ij,jk->ik", 2),
    ("ij,ij->", 2),
    ("ij,kj->ik", 2),
    ("i,j->ij", "vec2"),
    ("ij,j->i", "matvec"),
]


@pytest.mark.parametrize("expr,kind", EINSUM_CASES)
@pytest.mark.parametrize("split", [None, 0])
def test_einsum_grid(m, split, expr, kind):
    a6 = m[:6, :6]
    if kind == 1:
        args_np = (a6,)
    elif kind == 2:
        args_np = (a6, a6)
    elif kind == "vec2":
        args_np = (a6[0], a6[1])
    else:
        args_np = (a6, a6[0])
    args_ht = tuple(ht.array(x, split=split if np.ndim(x) > 1 else (0 if split == 0 else None)) for x in args_np)
    got = ht.einsum(expr, *args_ht)
    want = np.einsum(expr, *args_np)
    got_np = got.numpy() if hasattr(got, "numpy") else np.asarray(got)
    np.testing.assert_allclose(got_np, want, rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("split", [None, 0])
def test_vdot_inner_outer_kron(v, split):
    a = v[:12]
    b = v[12:]
    ha = ht.array(a, split=split)
    hb = ht.array(b, split=split)
    np.testing.assert_allclose(float(ht.vdot(ha, hb)), np.vdot(a, b), rtol=1e-12)
    np.testing.assert_allclose(float(ht.inner(ha, hb)), np.inner(a, b), rtol=1e-12)
    np.testing.assert_allclose(ht.outer(ha, hb).numpy(), np.outer(a, b), rtol=1e-12)
    m1 = np.arange(4.0).reshape(2, 2)
    m2 = np.arange(6.0).reshape(2, 3)
    np.testing.assert_allclose(
        ht.kron(ht.array(m1, split=split), ht.array(m2, split=split)).numpy(),
        np.kron(m1, m2),
        rtol=1e-12,
    )


def test_matrix_power_exponent_grid():
    a = np.array([[2.0, 1.0], [0.5, 3.0]])
    x = ht.array(a, split=0)
    for n in (0, 1, 3):
        np.testing.assert_allclose(
            ht.linalg.matrix_power(x, n).numpy(), np.linalg.matrix_power(a, n), rtol=1e-10
        )
    np.testing.assert_allclose(
        ht.linalg.matrix_power(x, -1).numpy(), np.linalg.matrix_power(a, -1), rtol=1e-8
    )


@pytest.mark.parametrize("split", [None, 0])
def test_tensordot_axes_forms(m, split):
    a = m[:6, :6]
    x = ht.array(a, split=split)
    np.testing.assert_allclose(
        ht.tensordot(x, x, axes=1).numpy(), np.tensordot(a, a, axes=1), rtol=1e-8
    )
    np.testing.assert_allclose(
        ht.tensordot(x, x, axes=([1], [0])).numpy(),
        np.tensordot(a, a, axes=([1], [0])),
        rtol=1e-8,
    )
    np.testing.assert_allclose(
        float(ht.tensordot(x, x, axes=2)), np.tensordot(a, a, axes=2), rtol=1e-8
    )


def test_trace_offsets(m):
    x = ht.array(m, split=0)
    for off in (-2, 0, 1, 3):
        np.testing.assert_allclose(
            float(ht.trace(x, offset=off)), np.trace(m, offset=off), rtol=1e-10
        )
