"""Admission control: per-tenant quotas and bounded queues, shed don't sink.

An overloaded batch system slows down; an overloaded *serving* system
must stay fast for the traffic it admits and refuse the rest loudly.
Two mechanisms, both evaluated before a request touches a coalescer
queue:

* **per-tenant token buckets** — each tenant refills at ``rate`` tokens
  per second up to ``burst``; a request costs one token per row.  A
  tenant over its quota is shed with a typed
  :class:`~heat_tpu.resilience.errors.OverloadedError`
  (``cause="quota"``, HTTP 429 with a computed ``Retry-After``) and
  never competes with in-quota tenants for batch slots — the isolation
  property the acceptance gate measures (an over-quota tenant hammers,
  in-quota p99 holds).
* **bounded admission depth** — at most ``HEAT_TPU_SERVE_QUEUE_DEPTH``
  rows may be queued-or-in-flight across the service; past it every
  tenant is shed (``cause="queue"``) instead of the queue growing
  without bound and collapsing tail latency for everyone.  The shed's
  ``Retry-After`` is computed from the **measured drain rate** (rows
  released over a sliding window): ``excess_rows / drain_rate``,
  clamped to [1 ms, 30 s] — so the fleet router and clients back off
  proportionally to how fast the queue actually moves, not by a coarse
  constant (``None`` before any drain has been observed).

Every decision is accounted in the metrics registry:
``serving.requests`` / ``serving.shed_quota`` / ``serving.shed_queue``
counters and the ``serving.queue_depth`` gauge — the signals a load
balancer or autoscaler watches on ``/metrics``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from ..analysis import tsan as _tsan
from ..resilience.errors import OverloadedError
from ..telemetry import metrics as _tm

__all__ = ["AdmissionController", "TokenBucket"]

_REQS_C = _tm.counter("serving.requests", "prediction requests admitted")
_SHED_QUOTA_C = _tm.counter(
    "serving.shed_quota", "requests shed by per-tenant quota (429)"
)
_SHED_QUEUE_C = _tm.counter(
    "serving.shed_queue", "requests shed by the bounded admission queue (429)"
)
_DEPTH_G = _tm.gauge(
    "serving.queue_depth", "rows admitted and not yet answered"
)


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.

    ``rate <= 0`` means unlimited (every take succeeds).  Not
    self-locking — the controller serializes access."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def take(self, cost: float = 1.0, now: Optional[float] = None) -> float:
        """Try to spend ``cost`` tokens; returns 0.0 on success or the
        seconds until enough tokens will have refilled (the 429
        ``Retry-After``)."""
        if self.rate <= 0:
            return 0.0
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= cost:
            self.tokens -= cost
            return 0.0
        return (cost - self.tokens) / self.rate


class AdmissionController:
    """Per-tenant quotas + one bounded admission count for the service.

    ``admit(tenant, rows)`` either accounts the rows in (returning a
    token the caller must ``release``) or raises
    :class:`OverloadedError`; unknown tenants get a bucket at the
    default rate/burst on first sight."""

    #: sliding window (seconds) over which the queue drain rate is
    #: estimated for queue-shed Retry-After computation
    DRAIN_WINDOW_S = 5.0

    def __init__(
        self,
        max_depth: int,
        default_rate: float = 0.0,
        default_burst: float = 64.0,
    ):
        self.max_depth = int(max_depth)
        self.default_rate = float(default_rate)
        self.default_burst = float(default_burst)
        self._buckets: Dict[str, TokenBucket] = {}
        self._depth = 0
        #: (monotonic, rows) per release inside the sliding window — the
        #: measured service drain rate a queue-caused shed's Retry-After
        #: is computed from (rows ahead / rows-per-second drained)
        self._drained: deque = deque()
        self._lock = _tsan.register_lock("serving.admission")

    def set_quota(self, tenant: str, rate: float, burst: Optional[float] = None) -> None:
        """Pin ``tenant``'s refill rate (rows/s) and burst (defaults to
        ``rate``, floor 1); replaces any existing bucket."""
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            self._buckets[tenant] = TokenBucket(
                rate, burst if burst is not None else max(rate, 1.0)
            )

    def admit(self, tenant: str, rows: int = 1) -> None:
        """Admit ``rows`` for ``tenant`` or raise :class:`OverloadedError`.

        Queue bound first (protects the process), quota second (bills
        the tenant only for admittable work)."""
        rows = max(1, int(rows))
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            if self._depth + rows > self.max_depth:
                _SHED_QUEUE_C.inc()
                retry_after = self._queue_retry_after(rows)
                raise OverloadedError(
                    f"admission queue full ({self._depth}/{self.max_depth} rows "
                    f"in flight); request of {rows} rows shed",
                    tenant=tenant,
                    cause="queue",
                    retry_after_s=retry_after,
                )
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    self.default_rate, self.default_burst
                )
            retry_after = bucket.take(rows)
            if retry_after > 0.0:
                _SHED_QUOTA_C.inc()
                raise OverloadedError(
                    f"tenant {tenant!r} over quota ({bucket.rate:g} rows/s, "
                    f"burst {bucket.burst:g}); retry in {retry_after:.3f}s",
                    tenant=tenant,
                    cause="quota",
                    retry_after_s=retry_after,
                )
            self._depth += rows
            _DEPTH_G.set(self._depth)
        _REQS_C.inc()

    def release(self, rows: int = 1) -> None:
        """Return ``rows`` previously admitted (request answered or
        failed); each release feeds the drain-rate window queue-shed
        Retry-After estimates are computed from."""
        rows = max(1, int(rows))
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            self._depth = max(0, self._depth - rows)
            _DEPTH_G.set(self._depth)
            self._drained.append((now, rows))
            self._prune(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.DRAIN_WINDOW_S
        while self._drained and self._drained[0][0] < cutoff:
            self._drained.popleft()

    def drain_rate(self) -> float:
        """Measured service drain rate (rows released per second over
        the sliding window), 0.0 before any release."""
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            now = time.monotonic()
            self._prune(now)
            if not self._drained:
                return 0.0
            rows = sum(r for _, r in self._drained)
            # span floor: a single just-now release must not read as an
            # (effectively infinite) instantaneous rate
            span = max(now - self._drained[0][0], 0.1)
            return rows / span

    def _queue_retry_after(self, rows: int) -> Optional[float]:
        """Retry-After for a queue-caused shed: how long until the queue
        has drained enough headroom for ``rows``, at the measured drain
        rate (caller holds the lock).  ``None`` before any drain has
        been observed — a cold process has no basis for an estimate and
        the coarse constant it would fabricate mis-paces every client."""
        now = time.monotonic()
        self._prune(now)
        if not self._drained:
            return None
        drained_rows = sum(r for _, r in self._drained)
        span = max(now - self._drained[0][0], 0.1)
        rate = drained_rows / span
        if rate <= 0.0:
            return None
        excess = self._depth + rows - self.max_depth
        return min(max(excess / rate, 0.001), 30.0)

    def depth(self) -> int:
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            return self._depth
