"""Fleet replica: one serving process, born warm, drained gracefully.

``python -m heat_tpu.fleet.replica`` runs ONE shared-nothing serving
replica: it loads its models from checkpoint directories, arms the AOT
executable cache, **pre-warms** every (model, bucket) program from the
manifest (reporting 503-not-ready with ``state: "warming"`` on
``/readyz`` the whole time), flips to ready, and serves ``/v1/*`` until
a SIGTERM starts a **graceful drain**: readiness goes
``state: "draining"`` (the router stops routing here), in-flight and
already-queued requests finish, then the process exits 0 — the
zero-failed-requests half of the replica-kill/drain gates.

:class:`LocalReplicaSet` is the process-management side — the
``ProcessSupervisor`` pattern (PR 8) pointed at serving replicas
instead of fit workers: spawn a replica subprocess (ephemeral port
published through a port file), wait for readiness, drain it with
SIGTERM (escalating to SIGKILL past the timeout), with per-replica log
files for postmortems.  The autoscaler drives it as its actuator; the
fleet bench and tests drive it directly.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..analysis import tsan as _tsan
from ..resilience.errors import WorkerLostError
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm

__all__ = ["LocalReplicaSet", "main"]

_SPAWNS_C = _tm.counter("fleet.replica_spawns", "replica subprocesses launched")
_STOPS_C = _tm.counter("fleet.replica_stops", "replica subprocesses drained/stopped")
_REPLICAS_G = _tm.gauge("fleet.replicas", "replica subprocesses currently managed")


class _Handle:
    """One managed replica subprocess."""

    __slots__ = ("proc", "url", "port", "log_path", "port_file", "index")

    def __init__(self, proc, url, port, log_path, port_file, index):
        self.proc = proc
        self.url = url
        self.port = port
        self.log_path = log_path
        self.port_file = port_file
        self.index = index


class LocalReplicaSet:
    """Spawn/drain serving-replica subprocesses on this host.

    ``models`` maps model name -> checkpoint directory; every replica
    loads all of them.  ``aot_cache``/``prewarm`` arm cold-start
    elimination: the first replica populates the AOT cache, every later
    one boots from it.  ``base_dir`` holds per-replica port files and
    logs."""

    def __init__(
        self,
        models: Dict[str, str],
        base_dir: str,
        aot_cache: Optional[str] = None,
        prewarm: Optional[str] = None,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        spawn_timeout_s: float = 120.0,
        env: Optional[dict] = None,
    ):
        self.models = dict(models)
        self.base_dir = os.path.abspath(base_dir)
        self.aot_cache = aot_cache
        self.prewarm = prewarm
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        self.queue_depth = queue_depth
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.base_env = dict(os.environ if env is None else env)
        self._handles: Dict[str, _Handle] = {}
        self._spawned = 0
        self._lock = _tsan.register_lock("fleet.replicas")
        os.makedirs(self.base_dir, exist_ok=True)

    # -- spawn ----------------------------------------------------------
    def _argv(self, port_file: str) -> List[str]:
        argv = [sys.executable, "-m", "heat_tpu.fleet.replica",
                "--port", "0", "--port-file", port_file]
        for name, directory in sorted(self.models.items()):
            argv += ["--model", f"{name}={directory}"]
        if self.aot_cache:
            argv += ["--aot-cache", self.aot_cache]
        if self.prewarm:
            argv += ["--prewarm", self.prewarm]
        if self.max_batch is not None:
            argv += ["--max-batch", str(int(self.max_batch))]
        if self.max_delay_ms is not None:
            argv += ["--max-delay-ms", str(float(self.max_delay_ms))]
        if self.queue_depth is not None:
            argv += ["--queue-depth", str(int(self.queue_depth))]
        return argv

    def _env(self) -> dict:
        env = dict(self.base_env)
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # a serving replica is one process on its own device set; the
        # parent's virtual-mesh XLA flags must not leak into it
        env.pop("XLA_FLAGS", None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def spawn(self, wait_ready: bool = True) -> str:
        """Launch one replica; returns its base URL (blocks until the
        replica reports ready unless ``wait_ready=False``, in which case
        it blocks only until the port is published).  Raises
        :class:`WorkerLostError` when the replica dies or the timeout
        expires first."""
        _inject("fleet.spawn")
        with self._lock:
            _tsan.note_access("fleet.replicas.table")
            index = self._spawned
            self._spawned += 1
        port_file = os.path.join(self.base_dir, f"replica-{index}.port")
        log_path = os.path.join(self.base_dir, f"replica-{index}.log")
        try:
            os.remove(port_file)
        except OSError:
            pass
        log_fd = os.open(log_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            proc = subprocess.Popen(
                self._argv(port_file), env=self._env(),
                stdout=log_fd, stderr=subprocess.STDOUT,
            )
        finally:
            os.close(log_fd)
        _SPAWNS_C.inc()
        port = self._await_port(proc, port_file, log_path)
        url = f"http://127.0.0.1:{port}"
        handle = _Handle(proc, url, port, log_path, port_file, index)
        with self._lock:
            _tsan.note_access("fleet.replicas.table")
            self._handles[url] = handle
            _REPLICAS_G.set(len(self._handles))
        if wait_ready:
            self._await_ready(handle)
        return url

    def _await_port(self, proc, port_file: str, log_path: str) -> int:
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise WorkerLostError(
                    f"replica died during startup (rc={proc.returncode}); "
                    f"log tail:\n{self._tail(log_path)}"
                )
            try:
                with open(port_file) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
        proc.kill()
        proc.wait()
        raise WorkerLostError(
            f"replica did not publish its port within {self.spawn_timeout_s:.0f}s; "
            f"log tail:\n{self._tail(log_path)}"
        )

    def _await_ready(self, handle: _Handle) -> None:
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if handle.proc.poll() is not None:
                raise WorkerLostError(
                    f"replica died before ready (rc={handle.proc.returncode}); "
                    f"log tail:\n{self._tail(handle.log_path)}"
                )
            try:
                with urllib.request.urlopen(handle.url + "/readyz", timeout=2.0):
                    return
            except urllib.error.HTTPError:
                time.sleep(0.1)  # up but warming (503)
            except Exception:  # lint: allow H501(socket not accepting yet; keep polling until the deadline)
                time.sleep(0.1)
        raise WorkerLostError(
            f"replica did not become ready within {self.spawn_timeout_s:.0f}s; "
            f"log tail:\n{self._tail(handle.log_path)}"
        )

    @staticmethod
    def _tail(path: str, limit: int = 2000) -> str:
        try:
            with open(path, "rb") as f:
                data = f.read()
            return data[-limit:].decode("utf-8", "replace")
        except OSError:
            return ""

    # -- drain / stop ---------------------------------------------------
    def drain_stop(self, url: str, timeout_s: float = 30.0) -> Optional[int]:
        """Gracefully stop one replica: SIGTERM (the replica drains and
        exits 0), SIGKILL past the timeout.  Returns the exit code, or
        None when the url is unknown."""
        with self._lock:
            _tsan.note_access("fleet.replicas.table")
            handle = self._handles.pop(url.rstrip("/"), None)
            _REPLICAS_G.set(len(self._handles))
        if handle is None:
            return None
        proc = handle.proc
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        _STOPS_C.inc()
        return proc.returncode

    def kill(self, url: str) -> Optional[int]:
        """SIGKILL one replica (the replica-loss scenario; no drain)."""
        with self._lock:
            _tsan.note_access("fleet.replicas.table")
            handle = self._handles.pop(url.rstrip("/"), None)
            _REPLICAS_G.set(len(self._handles))
        if handle is None:
            return None
        proc = handle.proc
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        _STOPS_C.inc()
        return proc.returncode

    def urls(self) -> List[str]:
        with self._lock:
            _tsan.note_access("fleet.replicas.table", write=False)
            return sorted(self._handles)

    def tail(self, url: str, limit: int = 2000) -> str:
        """The log tail of one managed replica (postmortems)."""
        with self._lock:
            _tsan.note_access("fleet.replicas.table", write=False)
            handle = self._handles.get(url.rstrip("/"))
        return self._tail(handle.log_path, limit) if handle is not None else ""

    def close(self, timeout_s: float = 30.0) -> None:
        """Drain-stop every managed replica.  Idempotent."""
        for url in self.urls():
            self.drain_stop(url, timeout_s=timeout_s)

    def __enter__(self) -> "LocalReplicaSet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


# ----------------------------------------------------------------------
# the replica process itself
# ----------------------------------------------------------------------
def _parse_models(specs: List[str]) -> List[Tuple[str, str]]:
    out = []
    for spec in specs:
        if "=" not in spec:
            raise SystemExit(f"--model needs name=directory, got {spec!r}")
        name, directory = spec.split("=", 1)
        out.append((name, directory))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m heat_tpu.fleet.replica`` — one serving replica."""
    import argparse

    ap = argparse.ArgumentParser(description="heat_tpu fleet serving replica")
    ap.add_argument("--model", action="append", default=[],
                    help="name=checkpoint-directory (repeatable)")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral, published via --port-file)")
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once serving")
    ap.add_argument("--prewarm", default=None,
                    help="pre-warm manifest path (export_prewarm_manifest)")
    ap.add_argument("--aot-cache", default=None,
                    help="AOT executable cache directory (HEAT_TPU_AOT_CACHE)")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-delay-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--drain-timeout-s", type=float, default=None)
    args = ap.parse_args(argv)

    from ..core import aot_cache
    from ..serving import InferenceService
    from ..telemetry import server as tserver

    if args.aot_cache:
        aot_cache.configure(args.aot_cache)

    svc = InferenceService(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        queue_depth=args.queue_depth,
    )
    svc.set_state("warming")
    url = svc.serve(args.port)
    if args.port_file:
        from ..resilience.atomic import atomic_write

        port = int(url.rsplit(":", 1)[1])
        with atomic_write(args.port_file, checksum=False) as tmp:
            with open(tmp, "w") as f:
                f.write(f"{port}\n")
    for name, directory in _parse_models(args.model):
        svc.load(name, directory)
    if args.prewarm:
        res = svc.prewarm(path=args.prewarm)
        print(f"replica prewarm: {json.dumps(res)}", flush=True)
    svc.set_state("ready")
    # prime the observatory before the first routed request: one forced
    # watermark sample so the router's very first /rooflinez poll sees a
    # real memory number, and a provenance line for the replica log
    from ..telemetry import observatory

    observatory.watermark_tick(force=True)
    print(
        f"replica observatory: enabled={observatory.armed()} "
        f"sync_every={observatory.sync_every()}",
        flush=True,
    )
    print(f"replica ready on {url}", flush=True)

    # SIGTERM -> graceful drain: readiness flips to "draining", the
    # router stops sending, in-flight work finishes, exit 0
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    stop.wait()
    drained = svc.drain(timeout=args.drain_timeout_s)
    tserver.stop_server()
    print(f"replica drained cleanly: {drained}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
