"""Data scalers, analog of heat/preprocessing/preprocessing.py
(StandardScaler :49, MinMaxScaler :158, Normalizer :284, MaxAbsScaler
:358, RobustScaler :444).  All are pure compositions of the distributed
ops layer (mean/var/min/max/percentile over the sharded sample axis).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from ..core import statistics, types
from ..core.base import BaseEstimator, TransformMixin
from ..core.dndarray import DNDarray

__all__ = ["StandardScaler", "MinMaxScaler", "Normalizer", "MaxAbsScaler", "RobustScaler"]


def _check_2d_float(x, name="X"):
    if not isinstance(x, DNDarray):
        raise TypeError(f"{name} must be a DNDarray, got {type(x)}")
    if not types.heat_type_is_inexact(x.dtype):
        return x.astype(types.float32)
    return x


class StandardScaler(BaseEstimator, TransformMixin):
    """Zero-mean unit-variance standardization (preprocessing.py:49)."""

    def __init__(self, copy: bool = True, with_mean: bool = True, with_std: bool = True):
        self.copy = copy
        self.with_mean = with_mean
        self.with_std = with_std
        self.mean_ = None
        self.var_ = None

    def fit(self, x: DNDarray, sample_weight=None) -> "StandardScaler":
        if sample_weight is not None:
            raise NotImplementedError("sample_weight is not yet supported (matching preprocessing.py:95)")
        x = _check_2d_float(x)
        self.mean_ = statistics.mean(x, axis=0) if self.with_mean else None
        if self.with_std:
            v = statistics.var(x, axis=0)
            # guard zero-variance features (preprocessing.py:120)
            vd = v._dense()
            v = DNDarray.from_dense(jnp.where(vd == 0, 1.0, vd), v.split, v.device, v.comm)
            self.var_ = v
        else:
            self.var_ = None
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        x = _check_2d_float(x)
        if self.with_mean and self.mean_ is not None:
            x = x - self.mean_
        if self.with_std and self.var_ is not None:
            from ..core import exponential

            x = x / exponential.sqrt(self.var_)
        return x

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        y = _check_2d_float(y, "Y")
        if self.with_std and self.var_ is not None:
            from ..core import exponential

            y = y * exponential.sqrt(self.var_)
        if self.with_mean and self.mean_ is not None:
            y = y + self.mean_
        return y


class MinMaxScaler(BaseEstimator, TransformMixin):
    """Rescale features to a range (preprocessing.py:158)."""

    def __init__(self, feature_range: Tuple[float, float] = (0.0, 1.0), copy: bool = True, clip: bool = False):
        if feature_range[0] >= feature_range[1]:
            raise ValueError(f"Minimum of desired feature range must be smaller than maximum, got {feature_range}")
        self.feature_range = feature_range
        self.copy = copy
        self.clip = clip
        self.data_min_ = None
        self.data_max_ = None
        self.scale_ = None
        self.min_ = None

    def fit(self, x: DNDarray) -> "MinMaxScaler":
        x = _check_2d_float(x)
        self.data_min_ = statistics.min(x, axis=0)
        self.data_max_ = statistics.max(x, axis=0)
        rng = self.data_max_._dense() - self.data_min_._dense()
        rng = jnp.where(rng == 0, 1.0, rng)
        lo, hi = self.feature_range
        scale = (hi - lo) / rng
        self.scale_ = DNDarray.from_dense(scale, None, x.device, x.comm)
        self.min_ = DNDarray.from_dense(lo - self.data_min_._dense() * scale, None, x.device, x.comm)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        x = _check_2d_float(x)
        y = x * self.scale_ + self.min_
        if self.clip:
            from ..core import rounding

            y = rounding.clip(y, self.feature_range[0], self.feature_range[1])
        return y

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        y = _check_2d_float(y, "Y")
        return (y - self.min_) / self.scale_


class Normalizer(BaseEstimator, TransformMixin):
    """Scale each sample to unit norm (preprocessing.py:284)."""

    def __init__(self, norm: str = "l2", copy: bool = True):
        if norm not in ("l1", "l2", "max"):
            raise NotImplementedError(f"norm must be 'l1', 'l2' or 'max', got {norm!r}")
        self.norm = norm
        self.copy = copy

    def fit(self, x: DNDarray) -> "Normalizer":
        return self  # stateless (preprocessing.py:320)

    def transform(self, x: DNDarray) -> DNDarray:
        x = _check_2d_float(x)
        dense = x._dense()
        if self.norm == "l2":
            n = jnp.sqrt(jnp.sum(dense * dense, axis=1, keepdims=True))
        elif self.norm == "l1":
            n = jnp.sum(jnp.abs(dense), axis=1, keepdims=True)
        else:
            n = jnp.max(jnp.abs(dense), axis=1, keepdims=True)
        n = jnp.where(n == 0, 1.0, n)
        return DNDarray.from_dense(dense / n, x.split, x.device, x.comm)


class MaxAbsScaler(BaseEstimator, TransformMixin):
    """Scale by the per-feature maximum absolute value (preprocessing.py:358)."""

    def __init__(self, copy: bool = True):
        self.copy = copy
        self.max_abs_ = None
        self.scale_ = None

    def fit(self, x: DNDarray) -> "MaxAbsScaler":
        x = _check_2d_float(x)
        from ..core import rounding

        m = statistics.max(rounding.abs(x), axis=0)
        md = jnp.where(m._dense() == 0, 1.0, m._dense())
        self.max_abs_ = m
        self.scale_ = DNDarray.from_dense(md, None, x.device, x.comm)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        x = _check_2d_float(x)
        return x / self.scale_

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        y = _check_2d_float(y, "Y")
        return y * self.scale_


class RobustScaler(BaseEstimator, TransformMixin):
    """Median/IQR scaling (preprocessing.py:444)."""

    def __init__(
        self,
        quantile_range: Tuple[float, float] = (25.0, 75.0),
        copy: bool = True,
        with_centering: bool = True,
        with_scaling: bool = True,
        unit_variance: bool = False,
    ):
        if unit_variance:
            raise NotImplementedError("unit_variance is not yet supported (matching preprocessing.py:500)")
        lo, hi = quantile_range
        if not 0 <= lo <= hi <= 100:
            raise ValueError(f"Invalid quantile range: {quantile_range}")
        self.quantile_range = quantile_range
        self.copy = copy
        self.with_centering = with_centering
        self.with_scaling = with_scaling
        self.unit_variance = unit_variance
        self.center_ = None
        self.iqr_ = None

    def fit(self, x: DNDarray) -> "RobustScaler":
        x = _check_2d_float(x)
        if self.with_centering:
            self.center_ = statistics.median(x, axis=0)
        if self.with_scaling:
            lo, hi = self.quantile_range
            q_lo = statistics.percentile(x, lo, axis=0)
            q_hi = statistics.percentile(x, hi, axis=0)
            iqr = q_hi._dense() - q_lo._dense()
            iqr = jnp.where(iqr == 0, 1.0, iqr)
            self.iqr_ = DNDarray.from_dense(iqr, None, x.device, x.comm)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        x = _check_2d_float(x)
        if self.with_centering and self.center_ is not None:
            x = x - self.center_
        if self.with_scaling and self.iqr_ is not None:
            x = x / self.iqr_
        return x

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        y = _check_2d_float(y, "Y")
        if self.with_scaling and self.iqr_ is not None:
            y = y * self.iqr_
        if self.with_centering and self.center_ is not None:
            y = y + self.center_
        return y
