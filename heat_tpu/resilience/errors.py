"""Typed failure taxonomy of the resilience layer.

The reference framework has exactly one failure mode: any raised
exception aborts the whole SPMD program.  This module splits failure
into classes the rest of the layer can act on mechanically:

* :class:`TransientFault` — a failure that a bounded retry is expected
  to clear (flaky filesystem, preempted bootstrap, injected test
  fault).  Subclasses ``OSError`` so the io retry filters treat real
  POSIX errors and injected transients identically.
* :class:`PermanentFault` — a failure retrying cannot fix.  The retry
  machinery re-raises it immediately, whatever the policy's filter
  says.
* :class:`ChecksumError` — a file's content does not match its CRC32
  sidecar: a torn or corrupted write that must fail loudly instead of
  returning garbage.  Never retried (the bytes on disk will not
  change).
* :class:`DivergenceError` — an iterative fit produced non-finite
  values.  Carries the last finite iterate and its iteration index so
  a caller can degrade gracefully (restart from ``last_good``, shrink
  the step, report a usable partial result).
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = [
    "ResilienceError",
    "TransientFault",
    "PermanentFault",
    "ChecksumError",
    "DivergenceError",
    "NoReplicaError",
    "OverloadedError",
    "PreemptedError",
    "ReshapeError",
    "WorkerLostError",
]


class ResilienceError(Exception):
    """Base of every failure type the resilience layer raises."""


class TransientFault(ResilienceError, OSError):
    """A retryable failure (also raised by the fault injector for
    ``kind='transient'`` plan entries)."""

    def __init__(self, message: str = "transient fault", site: Optional[str] = None, index: Optional[int] = None):
        super().__init__(message)
        self.site = site
        self.index = index


class PermanentFault(ResilienceError, RuntimeError):
    """A non-retryable failure: the retry machinery re-raises it
    immediately (also raised for ``kind='permanent'`` plan entries)."""

    def __init__(self, message: str = "permanent fault", site: Optional[str] = None, index: Optional[int] = None):
        super().__init__(message)
        self.site = site
        self.index = index


class ChecksumError(ResilienceError, OSError):
    """File content disagrees with its CRC32 sidecar.  Excluded from
    retry: re-reading corrupt bytes yields the same corrupt bytes."""

    def __init__(self, path: str, expected: int, actual: int):
        super().__init__(
            f"checksum mismatch for {path!r}: sidecar records crc32 "
            f"{expected:#010x} but the file hashes to {actual:#010x} — "
            "the file is torn or corrupted; restore it from a replica "
            "or delete the sidecar to force an unverified load"
        )
        self.path = path
        self.expected = expected
        self.actual = actual


class WorkerLostError(ResilienceError, RuntimeError):
    """A participant of the SPMD world stopped responding (preempted
    host, dead heartbeat, failed collective).  Carries what the detector
    knew: ``lost`` (how many participants are gone, best-effort),
    ``world_size`` (the size of the world the loss was observed in) and
    ``heartbeat_age`` (seconds since the last observed heartbeat, when
    heartbeat-based detection fired).  The elastic supervisor reacts by
    reshaping the mesh to the survivors and resuming from the last
    durable checkpoint; without a supervisor it propagates like any
    other fatal error."""

    def __init__(
        self,
        message: str = "worker lost",
        lost: int = 1,
        world_size: Optional[int] = None,
        heartbeat_age: Optional[float] = None,
    ):
        super().__init__(message)
        self.lost = int(lost)
        self.world_size = world_size
        self.heartbeat_age = heartbeat_age


class ReshapeError(ResilienceError, ValueError):
    """An elastic mesh reshape or a cross-world checkpoint restore
    cannot be performed: target world invalid (zero/negative, more
    devices than exist), or restored state does not fit the template
    (shape/dtype mismatch).  Never retried — the inputs will not
    change."""

    def __init__(
        self,
        message: str,
        old_size: Optional[int] = None,
        new_size: Optional[int] = None,
        leaf: Optional[str] = None,
    ):
        super().__init__(message)
        self.old_size = old_size
        self.new_size = new_size
        self.leaf = leaf


class OverloadedError(ResilienceError, RuntimeError):
    """The serving layer shed this request instead of queueing it.

    Deliberate load shedding, not a malfunction: either the caller's
    tenant is over its token-bucket quota (``cause="quota"``, with
    ``retry_after_s`` saying when the bucket will cover the request) or
    the service-wide admission queue is at its depth bound
    (``cause="queue"``).  The HTTP surface maps it to 429 with a
    ``Retry-After`` header.  Never retried by the resilience machinery
    — an immediate retry is exactly the traffic the shed exists to
    refuse; back off for ``retry_after_s`` instead."""

    def __init__(
        self,
        message: str = "overloaded",
        tenant: Optional[str] = None,
        cause: str = "queue",
        retry_after_s: Optional[float] = None,
    ):
        super().__init__(message)
        self.tenant = tenant
        self.cause = cause
        self.retry_after_s = retry_after_s


class PreemptedError(ResilienceError, RuntimeError):
    """A checkpointed batch fit yielded at a chunk boundary.

    Deliberate scheduling, not a malfunction: a latency spike (or an
    operator) asked the :class:`~heat_tpu.core.preempt.PreemptionGate`
    to reclaim the chips, and the fit paused at the first chunk boundary
    after the request — the point where its checkpoint (committed with
    ``converged=False``) already makes the pause durable.  Re-running
    the same fit with ``resume_from`` pointing at ``checkpoint_dir``
    continues the identical iteration sequence, so the resumed result is
    bitwise-equal to the uninterrupted fit.  Never retried by the
    resilience machinery — resuming *while the spike is still on* is
    exactly the contention the preemption exists to end."""

    def __init__(
        self,
        message: str = "fit preempted",
        iteration: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        reason: Optional[str] = None,
    ):
        super().__init__(message)
        self.iteration = iteration
        self.checkpoint_dir = checkpoint_dir
        self.reason = reason


class NoReplicaError(ResilienceError, RuntimeError):
    """The fleet router found no replica able to take a request: every
    replica hosting the model is unready (warming, draining, ejected by
    its circuit breaker) or unreachable, and bounded failover exhausted
    its attempts.  The HTTP surface maps it to a typed 503 with a
    ``Retry-After`` (the router's health-poll period: by then a probe
    or a recovered replica may have changed the verdict).  Never
    retried by the resilience machinery — the router already performed
    the bounded retry this error reports the failure of."""

    def __init__(
        self,
        message: str = "no replica available",
        model: Optional[str] = None,
        attempts: int = 0,
        retry_after_s: Optional[float] = None,
    ):
        super().__init__(message)
        self.model = model
        self.attempts = int(attempts)
        self.retry_after_s = retry_after_s


class DivergenceError(ResilienceError, ArithmeticError):
    """An iterative fit produced NaN/Inf.

    ``iteration`` is the first iteration at which non-finite values were
    observed; ``last_good`` is the most recent finite iterate (host
    numpy/None), so callers can resume or report it instead of silently
    converging to NaN.
    """

    def __init__(
        self,
        message: str,
        iteration: Optional[int] = None,
        last_good: Any = None,
        last_good_iteration: Optional[int] = None,
    ):
        super().__init__(message)
        self.iteration = iteration
        self.last_good = last_good
        self.last_good_iteration = last_good_iteration
