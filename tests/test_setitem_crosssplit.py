"""Cross-split setitem value-distribution grid (VERDICT r4 #6, second
family): the full (target split) x (value split) x (key kind) matrix from
the reference's setitem battery (heat/core/tests/test_dndarray.py), where
the VALUE being assigned is itself distributed differently from the
target.  Complements tests/test_setitem_widening.py (key-shape corners)
with the distribution grid.
"""

import numpy as np
import pytest

import heat_tpu as ht

TARGET_SPLITS = [None, 0, 1]
VALUE_SPLITS = [None, 0, 1]


def _fresh(split):
    base = np.arange(9 * 12, dtype=np.float32).reshape(9, 12)
    return ht.array(base.copy(), split=split), base.copy()


KEYS = [
    ("full", (slice(None), slice(None)), (9, 12)),
    ("rows", (slice(2, 7), slice(None)), (5, 12)),
    ("cols", (slice(None), slice(3, 10)), (9, 7)),
    ("block", (slice(1, 8), slice(2, 11)), (7, 9)),
    ("strided", (slice(0, 9, 2), slice(1, 12, 3)), (5, 4)),
    ("row", (4, slice(None)), (12,)),
]


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
@pytest.mark.parametrize("vsplit", VALUE_SPLITS)
def test_distributed_value_grid(tsplit, vsplit):
    rng = np.random.default_rng(0)
    for name, key, vshape in KEYS:
        x, base = _fresh(tsplit)
        val = rng.standard_normal(vshape).astype(np.float32)
        vs = vsplit if vsplit is None or vsplit < len(vshape) else None
        x[key] = ht.array(val, split=vs)
        base[key] = val
        np.testing.assert_array_equal(
            x.numpy(), base, err_msg=f"{name}: target={tsplit} value={vsplit}"
        )


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
def test_value_kinds(tsplit):
    rng = np.random.default_rng(1)
    val = rng.standard_normal((5, 12)).astype(np.float32)
    for kind, v in [
        ("numpy", val),
        ("list", val.tolist()),
        ("scalar", 7.25),
        ("0d", np.float32(3.5)),
    ]:
        x, base = _fresh(tsplit)
        x[2:7] = v
        base[2:7] = v
        np.testing.assert_allclose(x.numpy(), base, err_msg=f"{kind} target={tsplit}")


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
@pytest.mark.parametrize("vsplit", TARGET_SPLITS)
def test_broadcast_value_distributions(tsplit, vsplit):
    rng = np.random.default_rng(2)
    row = rng.standard_normal((12,)).astype(np.float32)
    x, base = _fresh(tsplit)
    vs = vsplit if vsplit in (None, 0) else None
    x[3:8] = ht.array(row, split=vs)  # (12,) broadcast over 5 rows
    base[3:8] = row
    np.testing.assert_array_equal(x.numpy(), base)


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
@pytest.mark.parametrize("vsplit", TARGET_SPLITS)
def test_uneven_extents_cross_split(tsplit, vsplit):
    # 13 x 10 does not divide the 8-device mesh on either axis
    base = np.zeros((13, 10), np.float32)
    x = ht.array(base.copy(), split=tsplit)
    val = np.arange(6 * 10, dtype=np.float32).reshape(6, 10)
    x[4:10] = ht.array(val, split=vsplit)
    base[4:10] = val
    np.testing.assert_array_equal(x.numpy(), base)
    counts, _ = (x.counts_displs() if tsplit is not None else ((), ()))
    if tsplit is not None:
        assert sum(counts) == 13 if tsplit == 0 else 10


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
def test_boolean_and_fancy_with_distributed_values(tsplit):
    x, base = _fresh(tsplit)
    mask = base[:, 0] > 40.0
    val = np.full((int(mask.sum()), 12), -1.0, np.float32)
    x[ht.array(mask, split=0 if tsplit == 0 else None)] = ht.array(
        val, split=0 if tsplit == 0 else None
    )
    base[mask] = val
    np.testing.assert_array_equal(x.numpy(), base)

    x2, base2 = _fresh(tsplit)
    idx = np.asarray([0, 3, 8])
    val2 = np.full((3, 12), 5.0, np.float32)
    x2[ht.array(idx)] = ht.array(val2, split=None)
    base2[idx] = val2
    np.testing.assert_array_equal(x2.numpy(), base2)


@pytest.mark.parametrize("tsplit", TARGET_SPLITS)
def test_dtype_cast_cross_split(tsplit):
    x, base = _fresh(tsplit)
    # f64 values into an f32 target: cast-on-write, numpy semantics
    val = (np.arange(5 * 12, dtype=np.float64).reshape(5, 12) + 0.5)
    x[0:5] = ht.array(val, split=0)
    base[0:5] = val.astype(np.float32)
    np.testing.assert_array_equal(x.numpy(), base)
