"""Precision & memory static analysis tests (ISSUE 12 tentpole).

The contract under test (docs/static_analysis.md, "Precision & memory
rules"):

* the jaxpr dtype-flow walker flags each J2xx hazard on a bad fixture
  and stays silent on the good twin — J201 unsanctioned float
  truncation, J202 long-axis low-precision accumulation (reductions AND
  scan carries), J203 unpinned low-precision contractions, J204
  precision-policy violations;
* the static peak-HBM estimator agrees with
  ``Compiled.memory_analysis()`` within 10% on real kernels, models
  donation aliasing and per-device sharding division, and emits J301
  against ``HEAT_TPU_HBM_BUDGET_BYTES``;
* the ``POLICIES`` registry is a pure literal covering every served
  estimator kind, the bf16 KMeans predict path passes its ``tolerance``
  contract while bitwise kinds ignore the knob bitwise-identically, and
  a mis-declared ``bitwise`` policy is REFUSED at registry load;
* the dispatch compile hook runs the new analyzers (scoped policy +
  peak estimates into /statusz), and ``python -m heat_tpu.analysis
  --rules J2,J3`` batch-checks the served predict programs;
* satellites: ``types.canonical_dtype`` property grid, the
  ``lint_gate.py --fix-stale`` pruning workflow over the now-empty
  baseline, and the compat-matrix lane driving both ``core/_compat.py``
  resolver branches.
"""

import ast
import json
import os
import subprocess
import sys
import warnings

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

import heat_tpu as ht
from heat_tpu import analysis
from heat_tpu.analysis import diagnostics, dtype_flow, memory_model
from heat_tpu.analysis import precision_policy as pp
from heat_tpu.analysis.precision_policy import POLICIES, PrecisionPolicyError
from heat_tpu.core import dispatch, types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

TOL_POLICY = {"mode": "tolerance", "rtol": 0.02,
              "compute_dtypes": ("float32", "bfloat16")}
BITWISE_POLICY = {"mode": "bitwise", "compute_dtypes": ("float32",)}


def rules(diags):
    return [d.rule for d in diags]


@pytest.fixture(autouse=True)
def _clean_state():
    prev = diagnostics.set_analysis_mode("0")
    prev_dt = pp.set_predict_dtype("")
    analysis.clear_diagnostics()
    memory_model.reset_estimates()
    yield
    diagnostics.set_analysis_mode(prev)
    pp.set_predict_dtype(prev_dt)
    analysis.clear_diagnostics()
    memory_model.reset_estimates()
    dispatch.clear_cache()


# ----------------------------------------------------------------------
# J201 — silent float truncation
# ----------------------------------------------------------------------
class TestJ201:
    X = jnp.ones((64, 8), jnp.float32)

    @staticmethod
    def _narrowing(a):
        return jnp.matmul(a.astype(jnp.bfloat16), a.astype(jnp.bfloat16).T,
                          preferred_element_type=jnp.float32)

    def test_unsanctioned_narrowing_flags(self):
        diags = dtype_flow.analyze_dtype_flow(self._narrowing, self.X)
        assert "J201" in rules(diags)
        d = next(d for d in diags if d.rule == "J201")
        assert d.details["from"] == "float32" and d.details["to"] == "bfloat16"

    def test_allowed_narrowing_clean(self):
        assert dtype_flow.analyze_dtype_flow(
            self._narrowing, self.X, allowed_narrowing=("bfloat16",)
        ) == []

    def test_tolerance_policy_sanctions(self):
        assert dtype_flow.analyze_dtype_flow(
            self._narrowing, self.X, policy=TOL_POLICY
        ) == []

    def test_bitwise_policy_does_not_sanction(self):
        got = rules(dtype_flow.analyze_dtype_flow(
            self._narrowing, self.X, policy=BITWISE_POLICY
        ))
        assert "J201" in got and "J204" in got

    def test_f64_to_f32_flags(self):
        x64 = jnp.ones((8,), jnp.float64)
        diags = dtype_flow.analyze_dtype_flow(
            lambda a: a.astype(jnp.float32) * 2.0, x64
        )
        assert rules(diags) == ["J201"]
        assert diags[0].details == {"from": "float64", "to": "float32",
                                    "is_input": True}

    def test_weak_scalar_and_widening_clean(self):
        # widening (J105's domain) and weak python scalars never J201
        assert dtype_flow.analyze_dtype_flow(
            lambda a, s: a.astype(jnp.float64) * s,
            jnp.ones((8,), jnp.float32), 2.0,
        ) == []


# ----------------------------------------------------------------------
# J202 — long-axis low-precision accumulation
# ----------------------------------------------------------------------
class TestJ202:
    XB = jnp.ones((4096, 8), jnp.bfloat16)

    @staticmethod
    def _bf16_reduce(a):
        return lax.reduce(a, np.asarray(0, jnp.bfloat16), lax.add, (0,))

    def test_long_axis_bf16_reduce_flags(self):
        diags = dtype_flow.analyze_dtype_flow(
            self._bf16_reduce, self.XB, allowed_narrowing=("bfloat16",)
        )
        assert rules(diags) == ["J202"]
        assert diags[0].details["extent"] == 4096
        assert diags[0].details["dtype"] == "bfloat16"

    def test_f32_accumulation_clean(self):
        def good(a):
            return lax.reduce(
                a.astype(jnp.float32), np.asarray(0, np.float32), lax.add, (0,)
            )
        assert dtype_flow.analyze_dtype_flow(good, self.XB) == []

    def test_short_axis_clean(self):
        short = jnp.ones((64, 8), jnp.bfloat16)
        assert dtype_flow.analyze_dtype_flow(self._bf16_reduce, short) == []

    def test_threshold_knob(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_J202_THRESHOLD", "8192")
        assert dtype_flow.analyze_dtype_flow(self._bf16_reduce, self.XB) == []
        monkeypatch.setenv("HEAT_TPU_J202_THRESHOLD", "32")
        short = jnp.ones((64, 8), jnp.bfloat16)
        assert rules(dtype_flow.analyze_dtype_flow(self._bf16_reduce, short)) == ["J202"]

    def test_jnp_sum_upcasts_clean(self):
        # jnp.sum accumulates f32 internally — must NOT flag
        assert dtype_flow.analyze_dtype_flow(
            lambda a: jnp.sum(a, axis=0), self.XB
        ) == []

    def test_long_bf16_scan_carry_flags(self):
        def scanned(c, xs):
            def body(c, x):
                return c + x, ()
            out, _ = lax.scan(body, c, xs)
            return out

        diags = dtype_flow.analyze_dtype_flow(
            scanned, jnp.zeros((8,), jnp.bfloat16),
            jnp.ones((2048, 8), jnp.bfloat16),
        )
        assert "J202" in rules(diags)
        d = next(d for d in diags if d.rule == "J202")
        assert d.details["primitive"] == "scan" and d.details["extent"] == 2048

    def test_f32_scan_carry_clean(self):
        def scanned(c, xs):
            def body(c, x):
                return c + x, ()
            out, _ = lax.scan(body, c, xs)
            return out

        assert dtype_flow.analyze_dtype_flow(
            scanned, jnp.zeros((8,), jnp.float32),
            jnp.ones((2048, 8), jnp.float32),
        ) == []


# ----------------------------------------------------------------------
# J203 — unpinned low-precision contraction
# ----------------------------------------------------------------------
class TestJ203:
    XB = jnp.ones((64, 8), jnp.bfloat16)

    def test_unpinned_bf16_matmul_flags(self):
        diags = dtype_flow.analyze_dtype_flow(lambda a: jnp.matmul(a, a.T), self.XB)
        assert rules(diags) == ["J203"]
        assert diags[0].details["operand_dtypes"] == ["bfloat16", "bfloat16"]

    def test_preferred_element_type_clean(self):
        assert dtype_flow.analyze_dtype_flow(
            lambda a: jnp.matmul(a, a.T, preferred_element_type=jnp.float32),
            self.XB,
        ) == []

    def test_highest_precision_clean(self):
        assert dtype_flow.analyze_dtype_flow(
            lambda a: jnp.matmul(a, a.T, precision=jax.lax.Precision.HIGHEST),
            self.XB,
        ) == []

    def test_f32_matmul_clean(self):
        x = jnp.ones((64, 8), jnp.float32)
        assert dtype_flow.analyze_dtype_flow(lambda a: jnp.matmul(a, a.T), x) == []


# ----------------------------------------------------------------------
# J204 — policy violations (walker-level; the choke points below)
# ----------------------------------------------------------------------
class TestJ204:
    def test_bf16_under_bitwise_flags(self):
        diags = dtype_flow.analyze_dtype_flow(
            lambda a: jnp.matmul(a, a.T, preferred_element_type=jnp.float32),
            jnp.ones((8, 8), jnp.bfloat16), policy=BITWISE_POLICY,
        )
        assert rules(diags) == ["J204"]
        assert diags[0].details["outside"] == ["bfloat16"]

    def test_bf16_under_tolerance_clean(self):
        assert dtype_flow.analyze_dtype_flow(
            lambda a: jnp.matmul(a, a.T, preferred_element_type=jnp.float32),
            jnp.ones((8, 8), jnp.bfloat16), policy=TOL_POLICY,
        ) == []

    def test_wider_than_native_not_a_violation(self):
        # f64 data through an f32-declared estimator IS the native path
        assert dtype_flow.analyze_dtype_flow(
            lambda a: a * 2.0, jnp.ones((8,), jnp.float64),
            policy=BITWISE_POLICY,
        ) == []

    def test_disallowed_predict_dtype_emits_once(self):
        pp.set_predict_dtype("bfloat16")
        before = len([d for d in analysis.recent_diagnostics()
                      if d.rule == "J204"])
        assert pp.compute_dtype("Lasso") == "float32"  # bitwise: knob ignored
        assert pp.compute_dtype("Lasso") == "float32"
        after = [d for d in analysis.recent_diagnostics() if d.rule == "J204"]
        assert len(after) == before + 1  # warned once, not per call
        assert pp.compute_dtype("KMeans") == "bfloat16"  # tolerance: honored


# ----------------------------------------------------------------------
# static peak-HBM estimator (J301)
# ----------------------------------------------------------------------
def _xla_peak(fn, args, donate=()):
    jf = jax.jit(fn, donate_argnums=donate)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ma = jf.lower(*args).compile().memory_analysis()
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "alias_size_in_bytes"):
        if not hasattr(ma, attr):
            pytest.skip("Compiled.memory_analysis lacks size attributes here")
    return (ma.argument_size_in_bytes + ma.output_size_in_bytes
            + ma.temp_size_in_bytes - ma.alias_size_in_bytes)


class TestMemoryModel:
    N = 256

    def _check(self, fn, args, donate=()):
        est = memory_model.estimate_peak(fn, *args, donate_argnums=donate)
        xla = _xla_peak(fn, args, donate)
        assert xla > 0
        # acceptance bound: the static prediction within 10% of XLA's
        # own memory analysis
        assert abs(est.per_device_bytes - xla) / xla < 0.10, (est, xla)
        return est

    def test_matmul_within_10pct(self):
        a = jnp.ones((self.N, self.N))
        self._check(lambda x, y: x @ y, (a, a))

    def test_elementwise_chain_within_10pct(self):
        a = jnp.ones((self.N, self.N))
        self._check(lambda x, y, z: x * y + z, (a, a, a))

    def test_reduction_within_10pct(self):
        a = jnp.ones((self.N, self.N))
        self._check(lambda x: x.sum(), (a,))

    def test_donated_update_within_10pct(self):
        a = jnp.ones((self.N, self.N))
        est = self._check(lambda x: x + 1.0, (a,), donate=(0,))
        assert est.aliased_bytes == a.nbytes

    def test_donation_halves_liveness(self):
        a = jnp.ones((1024, 1024))
        plain = memory_model.estimate_peak(lambda x: x + 1.0, a)
        donated = memory_model.estimate_peak(
            lambda x: x + 1.0, a, donate_argnums=(0,)
        )
        assert donated.per_device_bytes == plain.per_device_bytes - a.nbytes

    def test_sharded_division(self):
        comm = ht.WORLD
        if comm.size < 2:
            pytest.skip("needs a multi-device mesh")
        x = jax.device_put(
            jnp.ones((64 * comm.size, 16)),
            NamedSharding(comm.mesh, P(comm.axis_name, None)),
        )
        est = memory_model.estimate_peak(lambda v: v * 2.0, x)
        assert est.peak_bytes == 2 * x.nbytes
        assert est.per_device_bytes == est.peak_bytes // comm.size

    def test_budget_bad_good_fixture(self, monkeypatch):
        a = jnp.ones((512, 512))
        est = memory_model.estimate_peak(lambda x: x @ x, a)
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", str(est.per_device_bytes - 1))
        d = memory_model.check_budget(est, "fixture")
        assert d is not None and d.rule == "J301"
        assert d.details["budget_bytes"] == est.per_device_bytes - 1
        # good twin: a budget the program fits under
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", str(est.per_device_bytes))
        assert memory_model.check_budget(est, "fixture") is None
        monkeypatch.delenv("HEAT_TPU_HBM_BUDGET_BYTES")
        assert memory_model.check_budget(est, "fixture") is None  # unarmed

    def test_analyze_surfaces_j301(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "64")
        diags = analysis.analyze(lambda x: x * 2.0, jnp.ones((1024,)))
        assert "J301" in rules(diags)


# ----------------------------------------------------------------------
# the POLICIES registry
# ----------------------------------------------------------------------
class TestPoliciesRegistry:
    def test_pure_literal(self):
        src = open(os.path.join(
            REPO_ROOT, "heat_tpu", "analysis", "precision_policy.py"
        )).read()
        tree = ast.parse(src)
        table = None
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                getattr(t, "id", None) == "POLICIES" for t in node.targets
            ):
                table = ast.literal_eval(node.value)
        assert table == POLICIES  # statically parseable, value-identical

    def test_covers_every_served_kind(self):
        from heat_tpu.serving.model_io import SUPPORTED_KINDS

        assert set(POLICIES) == set(SUPPORTED_KINDS)
        for kind, pol in POLICIES.items():
            assert pol["mode"] in ("bitwise", "tolerance")
            assert pol["compute_dtypes"][0] == "float32"
            if pol["mode"] == "tolerance":
                assert pol["rtol"] > 0
            else:
                assert len(pol["compute_dtypes"]) == 1

    def test_validate_policy_rejects_malformed(self):
        with pytest.raises(ValueError):
            pp.validate_policy({"mode": "loose", "compute_dtypes": ("float32",)})
        with pytest.raises(ValueError):
            pp.validate_policy({"mode": "tolerance", "compute_dtypes": ("float32",)})
        with pytest.raises(ValueError):
            pp.validate_policy({"mode": "bitwise", "compute_dtypes": ("int7",)})
        ok = pp.validate_policy(
            {"mode": "tolerance", "rtol": 0.1, "compute_dtypes": ["float32"]}
        )
        assert ok["compute_dtypes"] == ("float32",)

    def test_scope_nesting_and_reset(self):
        assert pp.active_policy() is None
        with pp.scope("KMeans"):
            assert pp.active_policy()["mode"] == "tolerance"
            with pp.scope("Lasso"):
                assert pp.active_policy()["mode"] == "bitwise"
            assert pp.active_policy()["mode"] == "tolerance"
        assert pp.active_policy() is None


# ----------------------------------------------------------------------
# the bf16 KMeans predict path (tolerance) vs bitwise kinds
# ----------------------------------------------------------------------
def _blobs(n=192, f=8, k=4, spread=8.0):
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((k, f)) * spread
    x = centers[rng.integers(0, k, n)] + rng.standard_normal((n, f))
    return ht.array(x.astype(np.float32), split=None), centers.astype(np.float32)


class TestBf16Predict:
    def test_tolerance_gate_kmeans(self):
        # seeded at the true blob centers: every sample's margin to the
        # runner-up center is >> the bf16 distance error, so the
        # tolerance path must reproduce the labels exactly
        x, centers = _blobs(spread=16.0)
        km = ht.cluster.KMeans(n_clusters=4, init=ht.array(centers),
                               max_iter=4, random_state=0)
        km.fit(x)
        ref = np.asarray(km.predict(x)._dense())
        pp.set_predict_dtype("bfloat16")
        low = np.asarray(km.predict(x)._dense())
        # well-separated blobs: the tolerance-path labels must agree
        np.testing.assert_array_equal(ref, low)

        # and the compute core (the squared distances argmin compares)
        # stays inside the declared rtol of its scale
        from heat_tpu.spatial import distance

        xd, cd = x._dense(), km.cluster_centers_._dense()
        d_ref = np.asarray(distance._pairwise_sqeuclidean(xd, cd))
        d_low = np.asarray(distance._pairwise_sqeuclidean_bf16(xd, cd))
        scale = np.abs(d_ref).max()
        assert np.abs(d_ref - d_low).max() / scale < POLICIES["KMeans"]["rtol"]

    def test_bf16_program_is_j2_clean_under_scope(self):
        # the shipped low-precision op must pass its own lint: narrowing
        # sanctioned by the tolerance policy, accumulation pinned f32
        from heat_tpu.spatial import distance

        x = jnp.ones((32, 8), jnp.float32)
        diags = dtype_flow.analyze_dtype_flow(
            distance._pairwise_euclidean_bf16, x, x,
            policy=POLICIES["KMeans"],
        )
        assert diags == []
        # and unsanctioned it is exactly the J201 hazard (non-vacuous)
        assert "J201" in rules(dtype_flow.analyze_dtype_flow(
            distance._pairwise_euclidean_bf16, x, x
        ))

    def test_bitwise_kind_ignores_knob(self):
        x, _ = _blobs()
        kmed = ht.cluster.KMedians(n_clusters=4, init="random", max_iter=5,
                                   random_state=0)
        kmed.fit(x)
        ref = np.asarray(kmed.predict(x)._dense())
        pp.set_predict_dtype("bfloat16")
        again = np.asarray(kmed.predict(x)._dense())
        np.testing.assert_array_equal(ref, again)  # bitwise: knob is inert

    @staticmethod
    def _labeled_blobs(n, k=4, f=8, spread=16.0, seed=7):
        # labels = blob membership: every k-neighborhood is label-pure,
        # so a bf16 near-tie that permutes WHICH same-blob neighbors are
        # kept cannot change the vote — the label-bitwise contract is a
        # statement about margins, not about exact neighbor identity
        rng = np.random.default_rng(seed)
        centers = rng.standard_normal((k, f)) * spread
        assign = rng.integers(0, k, n)
        x = centers[assign] + rng.standard_normal((n, f))
        return x.astype(np.float32), assign.astype(np.int32)

    def test_knn_bf16_labels_bitwise(self):
        # the KNN tolerance contract covers the distance stage only: on
        # margin-separated blobs the bf16 neighbor search must
        # reproduce the predicted labels EXACTLY (ISSUE 16 satellite)
        xd, lab_d = self._labeled_blobs(160)
        x = ht.array(xd, split=None)
        lab = ht.array(lab_d, split=None)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=5)
        knn.fit(x, lab)
        ref = np.asarray(knn.predict(x)._dense())
        np.testing.assert_array_equal(ref, lab_d)  # sane reference
        pp.set_predict_dtype("bfloat16")
        low = np.asarray(knn.predict(x)._dense())
        np.testing.assert_array_equal(ref, low)

    def test_knn_bf16_distributed_ring_labels_bitwise(self):
        # split inputs take the ring-fused top-k; the lowp tile swap is
        # part of its cache key, so both variants coexist compiled
        if ht.WORLD.size < 2:
            pytest.skip("needs a multi-device mesh")
        xd, lab_d = self._labeled_blobs(192, seed=13)
        xs = ht.array(xd, split=0)
        lab = ht.array(lab_d, split=0)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=5)
        knn.fit(xs, lab)
        ref = np.asarray(knn.predict(xs)._dense())
        pp.set_predict_dtype("bfloat16")
        low = np.asarray(knn.predict(xs)._dense())
        np.testing.assert_array_equal(ref, low)

    def test_pca_transform_bf16_within_rtol(self):
        x, _ = _blobs(n=256, f=8)
        pca = ht.decomposition.PCA(n_components=4, svd_solver="full")
        pca.fit(x)
        ref = np.asarray(pca.transform(x)._dense())
        pp.set_predict_dtype("bfloat16")
        low = np.asarray(pca.transform(x)._dense())
        assert low.dtype == np.float32  # accumulation stayed pinned f32
        scale = np.abs(ref).max()
        assert np.abs(ref - low).max() / scale < POLICIES["PCA"]["rtol"]


# ----------------------------------------------------------------------
# registry enforcement (save_model -> ModelRegistry.load)
# ----------------------------------------------------------------------
class TestRegistryEnforcement:
    def _fitted_km(self):
        x, _ = _blobs()
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=4,
                               random_state=0)
        km.fit(x)
        return km

    def test_policy_recorded_and_roundtrips(self, tmp_path):
        from heat_tpu import serving

        km = self._fitted_km()
        serving.save_model(km, str(tmp_path), version=1, name="km")
        reg = serving.ModelRegistry()
        assert reg.load("km", str(tmp_path)) == 1
        rec = reg.record("km")
        assert rec["policy"]["mode"] == "tolerance"
        assert rec["meta"]["compute_dtype"] == "float32"

    def test_misdeclared_bitwise_rejected_at_load(self, tmp_path):
        from heat_tpu import serving

        km = self._fitted_km()
        pp.set_predict_dtype("bfloat16")  # export computes bf16...
        serving.save_model(
            km, str(tmp_path), version=1, name="km",
            policy={"mode": "bitwise", "compute_dtypes": ("float32",)},
        )  # ...while declaring bitwise f32
        reg = serving.ModelRegistry()
        with pytest.raises(PrecisionPolicyError) as ei:
            reg.load("km", str(tmp_path))
        assert ei.value.diagnostic.rule == "J204"
        # the refusal left the registry empty — nothing half-activated
        assert reg.model_names() == []

    def test_refusal_keeps_active_version_serving(self, tmp_path):
        from heat_tpu import serving

        km = self._fitted_km()
        good_dir, bad_dir = tmp_path / "good", tmp_path / "bad"
        serving.save_model(km, str(good_dir), version=1, name="km")
        pp.set_predict_dtype("bfloat16")
        serving.save_model(
            km, str(bad_dir), version=2, name="km",
            policy={"mode": "bitwise", "compute_dtypes": ("float32",)},
        )
        pp.set_predict_dtype("")
        reg = serving.ModelRegistry()
        reg.load("km", str(good_dir))
        with pytest.raises(PrecisionPolicyError):
            reg.load("km", str(bad_dir), version=2)
        assert reg.active_version("km") == 1  # canary refused, v1 serving

    def test_bitwise_process_rejects_tolerance_export(self, tmp_path):
        # exported under bf16, loaded into a process ALSO serving bf16:
        # fine for the tolerance policy; the same version re-declared
        # is covered above — here the recorded dtype check alone
        from heat_tpu import serving

        km = self._fitted_km()
        pp.set_predict_dtype("bfloat16")
        serving.save_model(km, str(tmp_path), version=1, name="km")
        reg = serving.ModelRegistry()
        assert reg.load("km", str(tmp_path)) == 1  # tolerance allows bf16
        assert reg.record("km")["meta"]["compute_dtype"] == "bfloat16"

    def test_legacy_meta_loads_unchecked(self, tmp_path):
        from heat_tpu import serving

        km = self._fitted_km()
        serving.save_model(km, str(tmp_path), version=1, name="km")
        # strip the policy fields the way a pre-ISSUE-12 writer would
        meta_path = os.path.join(str(tmp_path), "meta_1.json")
        meta = json.load(open(meta_path))
        meta.pop("policy", None)
        meta.pop("compute_dtype", None)
        from heat_tpu.resilience.atomic import atomic_write

        with atomic_write(meta_path) as tmp:
            with open(tmp, "w") as fh:
                json.dump(meta, fh)
        reg = serving.ModelRegistry()
        assert reg.load("km", str(tmp_path)) == 1


# ----------------------------------------------------------------------
# the dispatch compile hook + introspection surfaces
# ----------------------------------------------------------------------
class TestDispatchHookPrecision:
    def test_scoped_policy_checks_dispatch_compiles(self):
        diagnostics.set_analysis_mode("warn")
        dispatch.clear_cache()
        xb = jnp.ones((16, 8), jnp.bfloat16)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pp.scope("Lasso"):  # bitwise f32
                dispatch.eager_apply(jnp.matmul, (xb, xb.T))
        got = rules(analysis.recent_diagnostics())
        assert "J203" in got and "J204" in got

    def test_unscoped_bf16_dispatch_flags_j203_only(self):
        diagnostics.set_analysis_mode("warn")
        dispatch.clear_cache()
        xb = jnp.ones((16, 8), jnp.bfloat16)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            dispatch.eager_apply(jnp.matmul, (xb, xb.T))
        got = rules(analysis.recent_diagnostics())
        assert "J203" in got and "J204" not in got

    def test_estimates_recorded_and_budget_fires(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "128")
        diagnostics.set_analysis_mode("warn")
        dispatch.clear_cache()
        memory_model.reset_estimates()
        x = jnp.ones((1024, 8), jnp.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            dispatch.eager_apply(jnp.add, (x, x))
        assert "J301" in rules(analysis.recent_diagnostics())
        summary = memory_model.peak_summary()
        assert summary["budget_bytes"] == 128
        assert any(
            rec["per_device_bytes"] > 128 for rec in summary["estimates"].values()
        )

    def test_off_mode_records_nothing(self):
        assert diagnostics.analysis_mode() == "off"
        dispatch.clear_cache()
        memory_model.reset_estimates()
        xb = jnp.ones((16, 8), jnp.bfloat16)
        dispatch.eager_apply(jnp.matmul, (xb, xb.T))
        assert analysis.recent_diagnostics() == []
        assert memory_model.peak_summary()["estimates"] == {}

    def test_statusz_carries_analysis_section(self):
        diagnostics.set_analysis_mode("warn")
        dispatch.clear_cache()
        x = jnp.ones((64,), jnp.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            dispatch.eager_apply(jnp.multiply, (x, x))
        from heat_tpu.telemetry.server import statusz_report

        doc = statusz_report()
        assert doc["analysis"]["mode"] == "warn"
        assert doc["analysis"]["hbm"]["estimates"]  # the estimate landed

    def test_crash_bundle_carries_analysis_section(self):
        from heat_tpu.telemetry.flight_recorder import build_bundle

        diagnostics.emit(
            analysis.Diagnostic(rule="J301", message="m", location="l"),
            mode="off",
        )
        doc = build_bundle(reason="test")
        recent = doc["analysis"]["recent_diagnostics"]
        assert any(d["rule"] == "J301" for d in recent)


# ----------------------------------------------------------------------
# the --rules J2,J3 batch CLI
# ----------------------------------------------------------------------
class TestProgramBatchCLI:
    def test_served_predict_programs_are_clean(self, capsys):
        from heat_tpu.analysis.__main__ import main

        assert main(["--rules", "J2,J3", "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert set(doc["programs"]) == set(POLICIES)
        for kind, rec in doc["programs"].items():
            assert rec["diagnostics"] == []
        # the batch measured real programs, not nothing
        assert doc["programs"]["KMeans"]["predicted_peak_bytes"] > 0


# ----------------------------------------------------------------------
# satellite: types.canonical_dtype property grid (PR 1/8 invariants)
# ----------------------------------------------------------------------
GRID_DTYPES = [
    types.int8, types.int16, types.int32, types.int64,
    types.uint8, types.uint16, types.uint32, types.uint64,
    types.float16, types.bfloat16, types.float32, types.float64,
    types.complex64, types.complex128,
]


class TestCanonicalDtype:
    @pytest.mark.parametrize("t", GRID_DTYPES, ids=lambda t: t.__name__)
    def test_idempotent(self, t):
        once = types.canonical_dtype(t)
        assert types.canonical_dtype(once) == once

    @pytest.mark.parametrize("t", GRID_DTYPES, ids=lambda t: t.__name__)
    def test_never_widens_same_kind(self, t):
        # the J105 invariant: the canonical dtype is the same kind at
        # equal-or-smaller width — routing an astype through it can
        # never introduce silent same-kind widening
        req = np.dtype(t.jax_type())
        got = np.dtype(types.canonical_dtype(t))
        assert got.kind == req.kind or {got.kind, req.kind} <= {"V", "f"}
        assert got.itemsize <= req.itemsize

    @pytest.mark.parametrize("t", GRID_DTYPES, ids=lambda t: t.__name__)
    def test_spelling_agreement(self, t):
        # every spelling the migrated call sites use resolves identically
        jt = t.jax_type()
        expect = types.canonical_dtype(t)
        assert types.canonical_dtype(jt) == expect
        assert types.canonical_dtype(np.dtype(jt).name) == expect

    def test_x64_identity(self):
        # the suite runs with jax_enable_x64 — canonical is the identity
        assert jax.config.jax_enable_x64
        for t in GRID_DTYPES:
            assert np.dtype(types.canonical_dtype(t)) == np.dtype(t.jax_type())

    def test_x64_off_demotions(self):
        # the other half of the contract needs an x64-less process
        code = (
            "import jax, jax.numpy as jnp\n"
            "from heat_tpu.core import types\n"
            "assert not jax.config.jax_enable_x64\n"
            "import numpy as np\n"
            "pairs = {types.int64: jnp.int32, types.uint64: jnp.uint32,\n"
            "         types.float64: jnp.float32, types.complex128: jnp.complex64,\n"
            "         types.int32: jnp.int32, types.float32: jnp.float32,\n"
            "         types.bfloat16: jnp.bfloat16}\n"
            "for t, want in pairs.items():\n"
            "    got = types.canonical_dtype(t)\n"
            "    assert np.dtype(got) == np.dtype(want), (t, got)\n"
        )
        env = {k: v for k, v in os.environ.items() if k != "JAX_ENABLE_X64"}
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           cwd=REPO_ROOT, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr

    def test_call_site_agreement(self):
        # the PR 1/8 migrated sites produce exactly the canonical index
        # dtype (no UserWarning-spam astype requests)
        want = np.dtype(types.canonical_dtype(jnp.int64))
        from heat_tpu.core import statistics

        am = statistics.argmin(ht.array(np.ones((4, 2), np.float32)), axis=1)
        assert np.asarray(am._dense()).dtype == want


# ----------------------------------------------------------------------
# satellite: lint baseline at zero + --fix-stale pruning
# ----------------------------------------------------------------------
class TestLintGateFixStale:
    def test_repo_baseline_is_empty(self):
        doc = json.load(open(os.path.join(REPO_ROOT, "scripts",
                                          "lint_baseline.json")))
        assert doc["violations"] == []

    def test_repo_lints_clean_with_empty_baseline(self):
        from lint_gate import run_gate

        res = run_gate(quiet=True)
        assert res["new_count"] == 0 and res["baseline"] == 0

    def test_fix_stale_prunes_without_accepting(self, tmp_path):
        from lint_gate import run_gate

        d = tmp_path / "src"
        d.mkdir()
        (d / "mod.py").write_text("try:\n    go()\nexcept Exception:\n    pass\n")
        baseline = tmp_path / "b.json"
        run_gate(paths=[str(d)], baseline_path=str(baseline), update=True,
                 quiet=True)
        # fix the accepted violation, introduce a NEW one elsewhere
        (d / "mod.py").write_text("try:\n    go()\nexcept ValueError:\n    pass\n")
        (d / "new.py").write_text('f = open(p, "w")\n')
        res = run_gate(paths=[str(d)], baseline_path=str(baseline),
                       fix_stale=True, quiet=True)
        assert res["fixed_count"] == 1
        assert res["new_count"] == 1  # the gate still fails on the new one
        doc = json.load(open(baseline))
        assert doc["violations"] == []  # pruned, NOT regenerated-with-new
        res2 = run_gate(paths=[str(d)], baseline_path=str(baseline), quiet=True)
        assert res2["fixed_count"] == 0 and res2["new_count"] == 1


# ----------------------------------------------------------------------
# satellite: compat-matrix lane (both resolver branches)
# ----------------------------------------------------------------------
class TestCompatMatrix:
    def test_both_branches_green_on_wrapper_test(self, monkeypatch):
        import compat_matrix

        monkeypatch.setattr(
            compat_matrix, "SUBSET",
            ("tests/test_factories_comm.py::test_collective_wrappers",),
        )
        monkeypatch.setattr(compat_matrix, "DESELECT", ())
        monkeypatch.setattr(compat_matrix, "DESELECT_NATIVE", ())
        res = compat_matrix.run_matrix(quiet=True)
        assert res["count"] == 0, res
        assert res["branches"]["legacy"]["passed"] >= 1
        assert res["branches"]["native"]["passed"] >= 1

    def test_compat_force_validation(self):
        code = (
            "import os\n"
            "os.environ['HEAT_TPU_COMPAT_FORCE'] = 'bogus'\n"
            "try:\n"
            "    import heat_tpu.core._compat\n"
            "except ValueError as e:\n"
            "    raise SystemExit(0)\n"
            "raise SystemExit(1)\n"
        )
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                           env={**os.environ, "JAX_PLATFORMS": "cpu"},
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
