"""Distributed k-clustering demo (analog of examples/cluster/demo_kClustering.py).

Creates four spherical clusters along the space diagonal as a split-0
DNDarray sharded over the device mesh, then fits KMeans, KMedians and
KMedoids and reports how well each recovers the generating centers.  Run
it on any mesh size — single TPU chip, a pod slice, or a virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python demo_kClustering.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import numpy as np

import heat_tpu as ht


def main() -> None:
    # 4 spherical clusters centered at (±offset)*k along the diagonal
    # (ht.utils.data.create_spherical_dataset is the library version of the
    # generator the reference defines inline in its demo)
    data = ht.utils.data.create_spherical_dataset(
        num_samples_cluster=5000, radius=1.0, offset=4.0, random_state=1
    )
    o = 4.0
    reference_centers = np.array([[-o, -o, -o], [-o, o, -o], [o, -o, o], [o, o, o]])

    for name, estimator in (
        ("KMeans", ht.cluster.KMeans(n_clusters=4, init="kmeans++", random_state=7)),
        ("KMedians", ht.cluster.KMedians(n_clusters=4, init="kmeans++", random_state=7)),
        ("KMedoids", ht.cluster.KMedoids(n_clusters=4, init="kmeans++", random_state=7)),
    ):
        t0 = time.perf_counter()
        labels = estimator.fit_predict(data)
        fit_s = time.perf_counter() - t0
        centers = estimator.cluster_centers_.numpy()
        # match each estimated center to its nearest generating center
        d = np.linalg.norm(centers[:, None, :] - reference_centers[None, :, :], axis=2)
        err = float(d.min(axis=1).max())
        print(f"{name}: worst center recovery distance {err:.3f}")
        print(f"  centers:\n{np.round(centers, 2)}")
        counts = np.bincount(labels.numpy().astype(int).ravel(), minlength=4)
        print(f"  cluster sizes: {counts.tolist()}")
        # one-line observability summary: cumulative collective traffic,
        # XLA compile wall time, and this fit's iteration rate
        print(f"  {ht.telemetry.summary_line(estimator.n_iter_ / fit_s)}")


if __name__ == "__main__":
    main()
