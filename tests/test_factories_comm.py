"""Factory and communication-layer tests (reference: heat/core/tests/
test_factories.py 1108 LoC, test_communication.py 2494 LoC).  The comm
tests target the mesh facade: chunk math, counts/displs, sub-communication,
and the collective wrappers under shard_map."""

import numpy as np
import pytest

import heat_tpu as ht


# ---------------------------------------------------------------- factories


@pytest.mark.parametrize("split", [None, 0])
def test_arange_variants(ht, split):
    np.testing.assert_allclose(ht.arange(7, split=split).numpy(), np.arange(7))
    np.testing.assert_allclose(ht.arange(2, 11, split=split).numpy(), np.arange(2, 11))
    np.testing.assert_allclose(ht.arange(1, 10, 2, split=split).numpy(), np.arange(1, 10, 2))
    np.testing.assert_allclose(
        ht.arange(0.0, 1.0, 0.25, split=split).numpy(), np.arange(0.0, 1.0, 0.25)
    )


@pytest.mark.parametrize("split", [None, 0, 1])
def test_zeros_ones_empty_full(ht, split):
    for fac, npfac in ((ht.zeros, np.zeros), (ht.ones, np.ones)):
        a = fac((5, 6), dtype=ht.float32, split=split)
        np.testing.assert_allclose(a.numpy(), npfac((5, 6), np.float32))
    f = ht.full((5, 6), 3.5, split=split)
    np.testing.assert_allclose(f.numpy(), np.full((5, 6), 3.5))
    e = ht.empty((5, 6), split=split)
    assert e.shape == (5, 6)


def test_like_factories(ht):
    a = ht.arange(12, dtype=ht.float32, split=0).reshape((3, 4))
    for fac, want in (
        (ht.zeros_like, np.zeros((3, 4))),
        (ht.ones_like, np.ones((3, 4))),
    ):
        b = fac(a)
        assert b.split == a.split and b.dtype == a.dtype
        np.testing.assert_allclose(b.numpy(), want)
    c = ht.full_like(a, 9.0)
    np.testing.assert_allclose(c.numpy(), np.full((3, 4), 9.0))
    d = ht.empty_like(a)
    assert d.shape == (3, 4) and d.split == 0


def test_eye_identity(ht):
    np.testing.assert_allclose(ht.eye(5, split=0).numpy(), np.eye(5))
    np.testing.assert_allclose(ht.eye((4, 6), split=1).numpy(), np.eye(4, 6))
    np.testing.assert_allclose(ht.identity(3).numpy(), np.identity(3))


@pytest.mark.parametrize("num,endpoint", [(7, True), (10, False), (1, True)])
def test_linspace_logspace_geomspace(ht, num, endpoint):
    np.testing.assert_allclose(
        ht.linspace(-2.0, 3.0, num, endpoint=endpoint, split=0).numpy(),
        np.linspace(-2.0, 3.0, num, endpoint=endpoint),
        rtol=1e-6,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        ht.logspace(0.0, 2.0, num, endpoint=endpoint).numpy(),
        np.logspace(0.0, 2.0, num, endpoint=endpoint),
        rtol=1e-5,
    )
    if num > 1 or endpoint:
        np.testing.assert_allclose(
            ht.geomspace(1.0, 100.0, num, endpoint=endpoint).numpy(),
            np.geomspace(1.0, 100.0, num, endpoint=endpoint),
            rtol=1e-5,
        )


def test_meshgrid(ht):
    x = ht.arange(4, split=0)
    y = ht.arange(3)
    gx, gy = ht.meshgrid(x, y)
    nx, ny = np.meshgrid(np.arange(4), np.arange(3))
    np.testing.assert_allclose(gx.numpy(), nx)
    np.testing.assert_allclose(gy.numpy(), ny)


def test_array_copy_and_dtype_inference(ht):
    src = np.array([[1, 2], [3, 4]], np.int64)
    a = ht.array(src)
    assert a.dtype in (ht.int64, ht.int32)
    b = ht.array([1.0, 2.5])
    assert b.dtype in (ht.float32, ht.float64)
    c = ht.array(a)  # from DNDarray
    np.testing.assert_allclose(c.numpy(), src)
    d = ht.asarray(src)
    np.testing.assert_allclose(d.numpy(), src)


def test_array_is_split_ingestion(ht):
    # single-controller semantics: the passed array is this process's
    # pre-distributed data (the whole array on one host); it is wrapped
    # in place with the declared split, no reshard
    local = np.arange(6.0).reshape(2, 3)
    a = ht.array(local, is_split=0)
    assert a.split == 0
    np.testing.assert_allclose(a.numpy(), local)
    with pytest.raises(ValueError):
        ht.array(local, split=0, is_split=0)  # mutually exclusive


def test_from_partition_dict_roundtrip(ht):
    a = ht.arange(20, dtype=ht.float32, split=0).reshape((10, 2))
    parts = a.__partitioned__
    b = ht.from_partition_dict(parts)
    np.testing.assert_allclose(b.numpy(), a.numpy())


# ----------------------------------------------------------- communication


def test_chunk_covers_extent(ht):
    comm = ht.get_comm()
    for extent in (1, 7, 8, 13, 64):
        total = 0
        prev_stop = 0
        for r in range(comm.size):
            off, lshape, slices = comm.chunk((extent, 3), 0, rank=r)
            assert off == prev_stop or lshape[0] == 0
            total += lshape[0]
            prev_stop = off + lshape[0] if lshape[0] else prev_stop
        assert total == extent


def test_chunk_split_none_replicates(ht):
    comm = ht.get_comm()
    off, lshape, slices = comm.chunk((5, 4), None)
    assert off == 0 and lshape == (5, 4)


def test_counts_displs(ht):
    comm = ht.get_comm()
    counts, displs, shape = comm.counts_displs_shape((13, 2), 0)
    assert sum(counts) == 13
    assert displs[0] == 0
    for i in range(1, len(displs)):
        assert displs[i] == displs[i - 1] + counts[i - 1]


def test_sub_communication_split(ht):
    comm = ht.get_comm()
    if comm.size < 2:
        pytest.skip("needs >= 2 devices")
    sub = comm.split(list(range(comm.size // 2)))
    assert sub.size == comm.size // 2
    a = ht.arange(6, split=0, comm=sub)
    np.testing.assert_allclose(a.numpy(), np.arange(6))


def test_collective_wrappers(ht):
    """psum/all_gather/ppermute/all_to_all wrappers under shard_map."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._compat import shard_map

    comm = ht.get_comm()
    n = comm.size

    def body(x):
        s = comm.psum(x)
        g = comm.all_gather(x)
        idx = comm.axis_index()
        shifted = comm.ring_shift(x, 1)
        return s, g, shifted + 0 * idx

    x = jnp.arange(float(n)).reshape(n, 1)
    s, g, shifted = jax.jit(
        shard_map(
            body,
            mesh=comm.mesh,
            in_specs=P(comm.axis_name),
            out_specs=(P(comm.axis_name), P(comm.axis_name), P(comm.axis_name)),
            check_vma=False,
        )
    )(x)
    np.testing.assert_allclose(np.asarray(s).ravel(), [np.arange(n).sum()] * n)
    np.testing.assert_allclose(np.asarray(shifted).ravel(), np.roll(np.arange(n), 1))


def test_use_comm_and_sanitize(ht):
    comm = ht.get_comm()
    assert ht.sanitize_comm(None) is ht.get_comm()
    assert ht.sanitize_comm(comm) is comm
    ht.use_comm(comm)
    assert ht.get_comm() is comm
    with pytest.raises((TypeError, ValueError)):
        ht.sanitize_comm("not a comm")


def test_comm_equality_and_repr(ht):
    comm = ht.get_comm()
    assert comm == comm
    assert "Communication" in repr(comm) or "devices" in repr(comm)
    assert comm.is_distributed == (comm.size > 1)


# ----------------------------------------------------- multi-host comm API


def test_process_topology_single_controller(ht):
    comm = ht.get_comm()
    assert comm.process_count == 1
    assert comm.process_rank == 0
    assert comm.local_participants == list(range(comm.size))
    assert len(comm.local_devices) == comm.size


def test_process_chunk_covers_participants(ht):
    comm = ht.get_comm()
    # single process owns every participant: the process block is everything
    off, lshape, slices = comm.process_chunk((13, 4), 0)
    assert off == 0 and lshape == (13, 4)
    off, lshape, _ = comm.process_chunk((13, 4), None)
    assert off == 0 and lshape == (13, 4)
    # a process that owns no participants gets an empty block
    off, lshape, _ = comm.process_chunk((13, 4), 0, process=comm.process_count + 7)
    assert lshape[0] == 0


def test_parallel_init_single_host_noop(ht):
    import heat_tpu

    heat_tpu.parallel.init()  # no coordinator: single-controller no-op
    assert heat_tpu.parallel.is_initialized()
    a = heat_tpu.arange(5, split=0)
    assert float(a.sum()) == 10.0


def test_lazy_import_does_not_touch_backend():
    # regression: importing heat_tpu must not initialize the XLA backend
    # (jax.distributed.initialize would otherwise be impossible after import)
    import subprocess, sys

    code = (
        "import heat_tpu\n"
        "from jax._src import xla_bridge\n"
        "raise SystemExit(1 if xla_bridge._backends else 0)\n"
    )
    import os
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0
