"""Sparse matrices, halo exchange, checkpoint + profiling subsystems
(reference: heat/sparse/tests, dndarray halo tests)."""

import numpy as np
import pytest
from scipy import sparse as sp

import heat_tpu as ht


@pytest.fixture
def spdata():
    rng = np.random.default_rng(33)
    dense = rng.standard_normal((8, 6)).astype(np.float32)
    dense[dense < 0.4] = 0.0
    return dense


def test_sparse_csr_roundtrip(spdata):
    s = ht.sparse.sparse_csr_matrix(spdata, split=0)
    assert s.shape == (8, 6)
    assert s.gnnz == np.count_nonzero(spdata)
    np.testing.assert_allclose(s.todense().numpy(), spdata)
    # scipy ingestion
    s2 = ht.sparse.sparse_csr_matrix(sp.csr_matrix(spdata))
    np.testing.assert_allclose(s2.todense().numpy(), spdata)
    # CSR triple matches scipy
    ref = sp.csr_matrix(spdata)
    np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
    np.testing.assert_array_equal(np.asarray(s.indices), ref.indices)
    np.testing.assert_allclose(np.asarray(s.data), ref.data)


def test_sparse_csc(spdata):
    s = ht.sparse.sparse_csc_matrix(spdata, split=1)
    ref = sp.csc_matrix(spdata)
    np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
    np.testing.assert_allclose(s.todense().numpy(), spdata)
    with pytest.raises(ValueError):
        ht.sparse.sparse_csc_matrix(spdata, split=0)


def test_sparse_arithmetic(spdata):
    other = spdata.T.copy().T  # same shape
    other = np.roll(spdata, 1, axis=0)
    a = ht.sparse.sparse_csr_matrix(spdata)
    b = ht.sparse.sparse_csr_matrix(other)
    np.testing.assert_allclose((a + b).todense().numpy(), spdata + other, rtol=1e-6)
    np.testing.assert_allclose((a * b).todense().numpy(), spdata * other, rtol=1e-6)
    np.testing.assert_allclose(ht.sparse.add(a, b).todense().numpy(), spdata + other, rtol=1e-6)


def test_sparse_transpose_lnnz(spdata):
    s = ht.sparse.sparse_csr_matrix(spdata, split=0)
    t = s.T
    assert isinstance(t, ht.sparse.DCSC_matrix)
    np.testing.assert_allclose(t.todense().numpy(), spdata.T)
    assert s.lnnz == s.gnnz  # single process holds everything
    assert s.lindptr.shape[0] == s.lshape[0] + 1


def test_to_sparse_to_dense(spdata):
    d = ht.array(spdata, split=0)
    s = ht.sparse.to_sparse_csr(d)
    assert s.split == 0
    back = ht.sparse.to_dense(s)
    np.testing.assert_allclose(back.numpy(), spdata)


def test_halo():
    data = np.arange(32.0, dtype=np.float32).reshape(16, 2)
    a = ht.array(data, split=0)
    a.get_halo(1)
    # single process: whole array is local, halos are None
    assert a.array_with_halos.shape[0] >= a.lshape[0]
    with pytest.raises(TypeError):
        a.get_halo(1.5)
    with pytest.raises(ValueError):
        a.get_halo(-1)


def test_halo_shard_map():
    import jax.numpy as jnp

    from heat_tpu.parallel.halo import with_halos

    comm = ht.get_comm()
    p = comm.size
    rows = 2 * p  # two true rows per shard on any CI mesh
    data = jnp.arange(float(rows * 2)).reshape(rows, 2)
    a = ht.array(data, split=0)
    out = np.asarray(with_halos(comm, a.larray_padded, 1, 0))
    assert out.shape == (p, 4, 2)  # p shards of 2 rows + 2 halo rows
    # middle shard r: rows [2r-1 .. 2r+2]
    r = p // 2
    np.testing.assert_allclose(out[r, 1:3], np.asarray(data[2 * r : 2 * r + 2]))
    np.testing.assert_allclose(out[r, 0], np.asarray(data[2 * r - 1]))
    np.testing.assert_allclose(out[r, 3], np.asarray(data[2 * r + 2]))
    # edges zero-filled
    np.testing.assert_allclose(out[0, 0], 0.0)
    np.testing.assert_allclose(out[p - 1, 3], 0.0)


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    state = {
        "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)},
        "arr": ht.arange(10, dtype=ht.float32, split=0),
        "step": jnp.asarray(7),
    }
    ckpt = ht.utils.checkpoint.Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(0, state, extra_metadata={"epoch": 3})
    restored = ckpt.restore(0)
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(np.asarray(restored["arr"]), np.arange(10.0))
    assert ckpt.metadata(0) == {"epoch": 3}
    assert ckpt.latest_step() == 0


def test_profiling_monitor():
    import jax.numpy as jnp

    @ht.utils.profiling.monitor("bench_op")
    def op():
        return jnp.sum(jnp.ones((100, 100)))

    out = op()
    assert float(out) == 10000.0
    assert op.last_runtime is not None and op.last_runtime >= 0
    with ht.utils.profiling.annotate("region"):
        pass
