"""Compat-matrix smoke lane: force BOTH branches of the jax-API resolver.

``core/_compat.py`` resolves ``shard_map`` once at import — modern
top-level ``jax.shard_map`` when present, else the
``jax.experimental.shard_map`` adapter with ``check_vma`` -> ``check_rep``
translation.  Any given runner's jax exercises only ONE branch, so the
other can rot silently (ROADMAP 5a).  This lane runs the
collective-wrapper test subset under each branch in a subprocess:

* **legacy** — ``HEAT_TPU_COMPAT_FORCE=legacy``: the experimental
  adapter, even when the top-level API exists;
* **native** — ``HEAT_TPU_COMPAT_FORCE=native``: the top-level API.  On
  a jax without one (this runner's 0.4.x), a faithful modern-API
  simulator is installed as ``jax.shard_map`` before anything imports
  heat_tpu — the resolver then takes its native branch verbatim, and
  the call sites' modern ``check_vma`` keyword flows through it.

Wired into ``perf_ci.py`` as the hard-cap ``compat_matrix`` gate
(``max_count`` 0): a red test in EITHER branch fails the same perf_gate
run that guards the kernels.

    python scripts/compat_matrix.py [--format json]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the collective-wrapper subset: the shard_map wrapper test plus the
#: collective-program HLO assertions (minus the documented-environmental
#: PSRS lowering artifact, tests/KNOWN_FAILURES.md)
SUBSET = (
    "tests/test_factories_comm.py",
    "tests/test_collective_programs.py",
)
DESELECT = (
    "tests/test_collective_programs.py::TestProgramHLOs::test_psrs_collective_budget",
)

#: native-branch-only deselects: tests that spawn fresh subprocesses,
#: which inherit HEAT_TPU_COMPAT_FORCE=native but not the in-process
#: modern-API simulator (on a legacy-only jax the child would refuse the
#: forced branch — correctly, but irrelevantly to the wrapper subset)
DESELECT_NATIVE = (
    "tests/test_factories_comm.py::test_lazy_import_does_not_touch_backend",
)

#: installs a modern-API simulator when the runner's jax lacks one, then
#: hands off to pytest — executed via ``python -c`` so the monkeypatch
#: lands before jax/heat_tpu resolve anything
_NATIVE_PRELOADER = """
import jax
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _sm

    def shard_map(f=None, **kw):
        if "check_vma" in kw:
            kw.setdefault("check_rep", kw.pop("check_vma"))
        if f is None:
            return lambda g: _sm(g, **kw)
        return _sm(f, **kw)

    jax.shard_map = shard_map
import sys
import pytest
sys.exit(pytest.main(sys.argv[1:]))
"""


def _pytest_args(branch: str):
    args = ["-q", "-p", "no:cacheprovider", "-p", "no:randomly"]
    deselect = DESELECT + (DESELECT_NATIVE if branch == "native" else ())
    for d in deselect:
        args += ["--deselect", d]
    return args + list(SUBSET)


def run_branch(branch: str, quiet: bool = False) -> dict:
    """Run the subset under one resolver branch; returns
    ``{"branch", "returncode", "passed", "failed", "tail"}``."""
    env = dict(os.environ)
    env["HEAT_TPU_COMPAT_FORCE"] = branch
    env.setdefault("JAX_PLATFORMS", "cpu")
    if branch == "native":
        cmd = [sys.executable, "-c", _NATIVE_PRELOADER] + _pytest_args(branch)
    else:
        cmd = [sys.executable, "-m", "pytest"] + _pytest_args(branch)
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=900
    )
    out = proc.stdout + proc.stderr
    passed = failed = 0
    for line in out.splitlines():
        if " passed" in line or " failed" in line:
            for tok_n, tok_w in zip(line.split(), line.split()[1:]):
                if tok_w.startswith("passed") and tok_n.isdigit():
                    passed = int(tok_n)
                if tok_w.startswith("failed") and tok_n.isdigit():
                    failed = int(tok_n)
    res = {
        "branch": branch,
        "returncode": proc.returncode,
        "passed": passed,
        "failed": failed,
        "tail": out.strip().splitlines()[-6:],
    }
    if not quiet:
        status = "ok" if proc.returncode == 0 else "FAIL"
        print(f"compat[{branch}]: {passed} passed, {failed} failed [{status}]")
        if proc.returncode != 0:
            print("\n".join(res["tail"]))
    return res


def run_matrix(quiet: bool = False) -> dict:
    """Both branches; ``count`` is the number of red branches (the
    perf_ci ``max_count`` 0 gate statistic)."""
    branches = [run_branch("legacy", quiet=quiet),
                run_branch("native", quiet=quiet)]
    red = [b for b in branches if b["returncode"] != 0]
    return {
        "count": len(red),
        "max_count": 0,
        "branches": {b["branch"]: {k: b[k] for k in
                                   ("returncode", "passed", "failed")}
                     for b in branches},
        "items": [
            f"{b['branch']}: rc={b['returncode']} "
            f"({b['passed']} passed, {b['failed']} failed)"
            for b in red
        ],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args()
    res = run_matrix(quiet=args.format == "json")
    if args.format == "json":
        print(json.dumps(res, indent=1))
    sys.exit(1 if res["count"] else 0)


if __name__ == "__main__":
    main()
