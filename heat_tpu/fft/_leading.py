"""Leading-contraction 3-D FFT engine (r5, second generation).

The r5 interleaved engine (``_planar._rfft3_interleaved``) pays two
"re-pair transposes" between its three DFT dots — ~9.4 ms of the 27.6 ms
512^3 transform on the bench v5e, pure relayout moving zero new
information.  This engine removes them entirely:

* every DFT stage contracts the LEADING dim of the operand
  (``dot_general`` with lhs contracting dim 0 — the grad-style
  transposed dot the MXU runs natively; measured at full speed, same
  scheduled bytes as a minor-dim dot), so the stage's output cycles the
  axis order and the next transform axis arrives in front without any
  transpose;
* the complex pair lives in SEPARATE re/im planes; each stage is two
  dots against the concatenated ``[W_re | W_im]`` matrix plus one fused
  elementwise combine (the column blocks are lane-aligned slices);
* the real-input transform halves axis 0 to ``m = n0 // 2`` bins
  (perfect tile alignment, unlike the 257-bin half spectrum) and
  carries the Nyquist bin through a tiny side chain;
* the Hermitian extension — pass-count-bound in XLA (measured 12.5 ms:
  roll/rev/concat each materialize) — is a Pallas kernel that emits one
  output row per grid step: lower rows are DMA copies, upper rows are
  the mirrored source row rev-rolled THROUGH THE MXU (one permutation
  matrix on each side, manual bf16x2 split since Mosaic lowers only
  DEFAULT/HIGHEST dot precision; the permutation matrix is exact in
  bf16, so the error is the 2^-17 split truncation, below the HIGH
  matmul policy's own 2.5e-5).  Measured 4.5 ms.

Measured end to end on the bench v5e at 512^3 f32 (same session):
22.7 ms vs 27.6 interleaved / 65.4 r4 — 9.7 GB scheduled vs 13.5 /
43.1 — ~43% of the 48 B/element minimal-model bandwidth.  Reference
semantics: heat/fft/fft.py:100-137 (fftn), verified against
``np.fft.fftn`` to ~2.7e-5 relative (HIGH default policy).

Norm scaling is folded into the exit-stage matrices (host f64
constants), so every norm mode ships at the default-path cost.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "leading_eligible",
    "rfft2_leading",
    "rfft3_leading",
    "cfft3_leading",
    "cfftn_leading",
]


def _precision():
    from ._planar import _interleaved_precision

    return _interleaved_precision()


# ----------------------------------------------------------------------
# Byte-bounded weight cache — shared with _planar.py via _weight_cache
# (one LRU keyed by (builder, args), bounded by BYTES under
# HEAT_TPU_FFT_WEIGHT_CACHE_MB, eviction counter in the telemetry
# registry; see heat_tpu/fft/_weight_cache.py).  The legacy names are
# re-exported here because this module introduced the surface.
# ----------------------------------------------------------------------
from ._weight_cache import byte_lru as _byte_lru
from ._weight_cache import weight_cache_clear, weight_cache_stats


@_byte_lru
def _cs(n: int, inverse: bool):
    """Host f64 (cos, sign*sin) planes of the n-point DFT matrix."""
    j = np.arange(n, dtype=np.float64)
    jk = np.outer(j, j) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    return np.cos(ang), sign * np.sin(ang)


@_byte_lru
def _w_entry_half(n: int, m: int, dt: str, part: str):
    """(n, m) real-input entry matrix for bins 0..m-1 (axis-0 halving)."""
    c, s = _cs(n, False)
    w = c if part == "re" else s
    return np.asarray(w[:, :m], dt)


@_byte_lru
def _w_entry_cat(n: int, m: int, dt: str):
    """(n, 2m) ``[re-bins 0..m-1 | im-bins 0..m-1]`` entry matrix: one
    dot reads x once (the two-dot form reads it twice); the mid stage's
    kernel picks the column blocks apart via BlockSpec index maps, so
    the halves are never slice-copied."""
    c, s = _cs(n, False)
    return np.asarray(np.concatenate([c[:, :m], s[:, :m]], 1), dt)


@_byte_lru
def _w_cat(n: int, dt: str, inverse: bool, scale: float):
    """(n, 2n) ``[W_re | W_im] * scale`` stage matrix (scale folds the
    norm factor into the exit stage — no post-scaling pass)."""
    c, s = _cs(n, inverse)
    return np.asarray(np.concatenate([c, s], 1) * scale, dt)


@_byte_lru
def _w_cat_im(n: int, dt: str, inverse: bool, scale: float):
    """(n, 2n) ``[-W_im | W_re] * scale``: the imaginary plane's column
    partner of ``_w_cat`` — ``re @ _w_cat + im @ _w_cat_im`` lands the
    combined (re | im) output bins in one cat tensor, so the complex
    entry needs no separate combine pass."""
    c, s = _cs(n, inverse)
    return np.asarray(np.concatenate([-s, c], 1) * scale, dt)


@_byte_lru
def _w_block(n: int, dt: str, inverse: bool, scale: float):
    """(2, n, 2, n) pair-block stage matrix: the complex multiply as 2x2
    real blocks, ``W[p, j, q, k]`` mapping input pair-plane p (0 = re,
    1 = im) and source index j to output pair-plane q and bin k.  One
    ``dot_general`` contracting (axis, pair) against dims (1, 0) runs a
    whole complex DFT stage — the operand pair shares ONE relayout where
    the separate-plane form pays two."""
    c, s = _cs(n, inverse)
    w = np.empty((2, n, 2, n), np.float64)
    w[0, :, 0, :] = c
    w[1, :, 0, :] = -s
    w[0, :, 1, :] = s
    w[1, :, 1, :] = c
    return np.asarray(w * scale, dt)


@_byte_lru
def _perm_bf(n: int):
    """Exact-in-bf16 rev-roll permutation: P[a, b] = 1 iff a = (n-b) % n.

    Symmetric (the map is an involution), so one matrix serves both the
    sublane and the lane side of the extension kernel's MXU reversal.
    Host numpy, like every other weight cache here — converted at the
    pallas_call boundary (a cached device array would pin HBM for the
    process lifetime and go stale across backend re-initialization)."""
    p = np.zeros((n, n), np.float32)
    p[(n - np.arange(n)) % n, np.arange(n)] = 1.0
    return np.asarray(p, jnp.bfloat16)


def _precision_is_high() -> bool:
    """The Pallas kernels' manual bf16 splits ARE the HIGH error class;
    any other requested precision must take the XLA paths."""
    from ..core._env import precision_name_from_env

    return precision_name_from_env("HEAT_TPU_FFT_PRECISION", "high") == "high"


def _dg(a: jax.Array, w, dims, prec) -> jax.Array:
    """``dot_general`` with the dtype strategy of the engine: f32 runs
    at the requested precision; f64 on TPU (no native f64 MXU path)
    runs a hi/lo split-precision contraction — each operand split into
    an f32 head plus an f32 residual, three HIGHEST f32 dots
    (``ah*wh + al*wh + ah*wl``) summed in f64.  Same technique as the
    bf16x3 fused-stage split, one level up; on CPU/GPU f64 contracts
    natively at full precision."""
    w = jnp.asarray(w)
    if a.dtype == jnp.float64 and jax.default_backend() == "tpu":
        ah = a.astype(jnp.float32)
        al = (a - ah.astype(jnp.float64)).astype(jnp.float32)
        wh = w.astype(jnp.float32)
        wl = (w - wh.astype(jnp.float64)).astype(jnp.float32)

        def d(x, y):
            return jax.lax.dot_general(
                x, y, dims,
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )

        head = d(ah, wh).astype(jnp.float64)
        corr = (d(al, wh) + d(ah, wl)).astype(jnp.float64)
        return head + corr
    return jax.lax.dot_general(a, w, dims, precision=prec)


def _dg0(a: jax.Array, w, prec) -> jax.Array:
    """Leading-dim contraction: (K, ...rest) x (K, N) -> (...rest, N)."""
    return _dg(a, w, (((0,), (0,)), ((), ())), prec)


def _stage(re, im, wcat, n: int, prec):
    """One complex DFT stage over the LEADING dim: two cat-dots + fused
    combine.  Output planes have the transformed axis's bins in the
    minor dim and the former trailing dims rotated to the front."""
    zr = _dg0(re, wcat, prec)
    zi = _dg0(im, wcat, prec)
    return zr[..., :n] - zi[..., n:], zr[..., n:] + zi[..., :n]


# ----------------------------------------------------------------------
# Fused stage kernel: both cat-dots + the plane combine in one pass, so
# the (zr, zi) intermediates never round-trip HBM — the XLA stage's
# combine alone re-reads 2x and re-writes 1x the stage volume.  The dots
# run as manual bf16x3 splits (x_hi*w_hi + x_lo*w_hi + x_hi*w_lo), the
# same error class as the HIGH matmul policy the engine defaults to
# (measured 1.2e-5 relative agreement); when HEAT_TPU_FFT_PRECISION
# demands HIGHEST the XLA stage runs instead.  Measured at the 512^3 mid
# stage: 4.44 ms vs 6.69 (the 4.2 ms bf16x3 MXU bound plus DMA overlap).
# ----------------------------------------------------------------------
@_byte_lru
def _w_cat_bf(n: int, inverse: bool, scale: float):
    """(w_hi, w_lo) bf16 split of the (n, 2n) cat stage matrix."""
    w = np.asarray(_w_cat(n, "float32", inverse, scale))
    hi = w.astype(np.float32).astype(jnp.bfloat16)
    lo = (w - np.asarray(hi, np.float32)).astype(jnp.bfloat16)
    return np.asarray(hi), np.asarray(lo)


def _stage_kernel_factory(n: int):
    from jax.experimental import pallas as pl

    def kern(wh_ref, wl_ref, re_ref, im_ref, ore_ref, oim_ref):
        wh = wh_ref[...]
        wl = wl_ref[...]

        def d(a, b):
            return jax.lax.dot_general(
                a, b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        def cat_dot(x):
            xh = x.astype(jnp.bfloat16)
            xl = (x - xh.astype(jnp.float32)).astype(jnp.bfloat16)
            return d(xh, wh) + d(xl, wh) + d(xh, wl)

        zr = cat_dot(re_ref[...])  # (TM, 2n)
        zi = cat_dot(im_ref[...])
        ore_ref[...] = zr[:, :n] - zi[:, n:]
        oim_ref[...] = zr[:, n:] + zi[:, :n]

    return kern


def _stage_tile(m_total: int) -> Optional[int]:
    for tm in (256, 128):
        if m_total % tm == 0:
            return tm
    return None


def _use_fused_stage(k: int, m_total: int, n: int) -> bool:
    if os.environ.get("HEAT_TPU_FFT_STAGE_PALLAS", "1") != "1":
        return False
    if jax.default_backend() != "tpu":
        return False
    if not _precision_is_high():
        return False
    # resident W pair: 2 * (n * 2n) bf16 — cap at ~8 MB of VMEM
    if n > 1024 or n % 128 != 0 or k % 8 != 0:
        return False
    return _stage_tile(m_total) is not None


def _stage_call(n, k, m_total, tm, re_map, im_map, re_op, im_op, inverse, scale):
    """Shared ``pallas_call`` scaffold of both fused-stage entries: the
    variants differ only in how their input index maps address the re/im
    planes (separate arrays vs column blocks of one cat tensor)."""
    from jax.experimental import pallas as pl

    wh, wl = _w_cat_bf(n, inverse, scale)
    return pl.pallas_call(
        _stage_kernel_factory(n),
        grid=(m_total // tm,),
        in_specs=[
            pl.BlockSpec((k, 2 * n), lambda i: (0, 0)),
            pl.BlockSpec((k, 2 * n), lambda i: (0, 0)),
            pl.BlockSpec((k, tm), re_map),
            pl.BlockSpec((k, tm), im_map),
        ],
        out_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0)),
            pl.BlockSpec((tm, n), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_total, n), re_op.dtype),
            jax.ShapeDtypeStruct((m_total, n), im_op.dtype),
        ],
        interpret=jax.default_backend() != "tpu",
    )(wh, wl, re_op, im_op)


def _stage_fused_pallas(re, im, n: int, inverse: bool, scale: float):
    """Fused stage on 2-D views: (K, M) planes -> (M, n) planes."""
    k = int(re.shape[0])
    rest = tuple(int(s) for s in re.shape[1:])
    m_total = 1
    for s in rest:
        m_total *= s
    tm = _stage_tile(m_total)
    ore, oim = _stage_call(
        n, k, m_total, tm,
        lambda i: (0, i), lambda i: (0, i),
        re.reshape(k, m_total), im.reshape(k, m_total),
        inverse, scale,
    )
    return ore.reshape(*rest, n), oim.reshape(*rest, n)


def _stage_fused_pallas_blocked(z, n: int, m: int, inverse: bool, scale: float):
    """Fused stage reading a BLOCK-CAT operand: z is (K, B, 2m) with re
    bins in columns [0, m) and im bins in [m, 2m) of every B-row — the
    entry dot's natural output.  The re/im halves are addressed by
    BlockSpec index maps (tile i of the re plane is block ``b*(2m/tm)+j``
    of the flat view), so no slice ever materializes."""
    k = int(z.shape[0])
    b = int(z.shape[1])
    m_total = b * m
    tm = _stage_tile(m)  # tiles must stay inside one m-block
    z2 = z.reshape(k, b * 2 * m)
    per_m = m // tm

    def re_map(i):
        return (0, (i // per_m) * (2 * per_m) + (i % per_m))

    def im_map(i):
        return (0, (i // per_m) * (2 * per_m) + per_m + (i % per_m))

    ore, oim = _stage_call(
        n, k, m_total, tm, re_map, im_map, z2, z2, inverse, scale
    )
    return ore.reshape(b, m, n), oim.reshape(b, m, n)


def _stage_auto(re, im, n: int, inverse: bool, scale: float, prec):
    """Fused kernel when eligible, else the XLA cat-dot stage (with the
    scale folded into the matrix either way)."""
    k = int(re.shape[0])
    m_total = 1
    for s in re.shape[1:]:
        m_total *= int(s)
    # the fused kernel's bf16x3 split is an f32 error class — f64 (and
    # any other dtype) must take the XLA stage
    if re.dtype == jnp.float32 and _use_fused_stage(k, m_total, n):
        return _stage_fused_pallas(re, im, n, inverse, scale)
    dt = str(re.dtype)
    return _stage(re, im, _w_cat(n, dt, inverse, float(scale)), n, prec)


# ----------------------------------------------------------------------
# Pair-block stages: the complex pair rides ONE tensor with the pair
# axis second-minor (bins minor — a trailing dim of 2 would fight the
# lane tiling), and each stage is a single dot_general against the
# (2, n, 2, n) block matrix.  Versus the separate-plane form this
# halves the number of operand relayouts per stage (the measured
# complex-vs-real gap at 512^3: 38.9 ms vs 18.5) and deletes the
# combine pass outright — the 2x2 block structure IS the combine.
# ----------------------------------------------------------------------
def _stage_pair(z: jax.Array, n: int, inverse: bool, scale: float, prec):
    """(n, ...rest, 2, m) -> (...rest, m, 2, k): one leading+pair
    contraction; the transformed axis's bins land minor and the axis
    order cycles exactly like the separate-plane stage."""
    dt = str(z.dtype)
    wb = _w_block(n, dt, inverse, float(scale))
    return _dg(z, wb, (((0, z.ndim - 2), (1, 0)), ((), ())), prec)


def _pair_kernel_factory(n: int):
    from jax.experimental import pallas as pl  # noqa: F401 (TPU lowering)

    def kern(wh_ref, wl_ref, re_ref, im_ref, o_ref):
        wh = wh_ref[...]
        wl = wl_ref[...]

        def d(a, b):
            return jax.lax.dot_general(
                a, b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        def cat_dot(x):
            xh = x.astype(jnp.bfloat16)
            xl = (x - xh.astype(jnp.float32)).astype(jnp.bfloat16)
            return d(xh, wh) + d(xl, wh) + d(xh, wl)

        zr = cat_dot(re_ref[...])  # (TM, 2n)
        zi = cat_dot(im_ref[...])
        o_ref[:, :n] = zr[:, :n] - zi[:, n:]
        o_ref[:, n:] = zr[:, n:] + zi[:, :n]

    return kern


def _pair_call(n, k, m_total, tm, re_map, im_map, re_op, im_op, inverse, scale):
    """``pallas_call`` scaffold of the fused pair stages: same input
    addressing as ``_stage_call`` but ONE cat-layout (m_total, 2n)
    output — the caller reshapes the minor dim to (2, n), restoring the
    pair-second-minor invariant without a copy pass."""
    from jax.experimental import pallas as pl

    wh, wl = _w_cat_bf(n, inverse, scale)
    return pl.pallas_call(
        _pair_kernel_factory(n),
        grid=(m_total // tm,),
        in_specs=[
            pl.BlockSpec((k, 2 * n), lambda i: (0, 0)),
            pl.BlockSpec((k, 2 * n), lambda i: (0, 0)),
            pl.BlockSpec((k, tm), re_map),
            pl.BlockSpec((k, tm), im_map),
        ],
        out_specs=pl.BlockSpec((tm, 2 * n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m_total, 2 * n), re_op.dtype),
        interpret=jax.default_backend() != "tpu",
    )(wh, wl, re_op, im_op)


def _stage_pair_fused(z, n: int, inverse: bool, scale: float):
    """Fused pair stage: z is (K, ...rest, 2, m); the flat (K, B*2m)
    view's re/im column blocks are addressed by BlockSpec index maps
    (as in ``_stage_fused_pallas_blocked``) and the output arrives
    already combined in cat layout."""
    k = int(z.shape[0])
    rest = tuple(int(s) for s in z.shape[1:-2])
    m = int(z.shape[-1])
    b = 1
    for s in rest:
        b *= s
    m_total = b * m
    tm = _stage_tile(m)  # tiles must stay inside one m-block
    z2 = z.reshape(k, b * 2 * m)
    per_m = m // tm

    def re_map(i):
        return (0, (i // per_m) * (2 * per_m) + (i % per_m))

    def im_map(i):
        return (0, (i // per_m) * (2 * per_m) + per_m + (i % per_m))

    out = _pair_call(n, k, m_total, tm, re_map, im_map, z2, z2, inverse, scale)
    return out.reshape(*rest, m, 2, n)


def _entry_pair_fused(re, im, n: int, inverse: bool):
    """Fused complex ENTRY: separate (K, ...rest) planes in, cat-layout
    pair tensor out — the XLA entry's two cat-dots + add collapse into
    one VMEM pass."""
    k = int(re.shape[0])
    rest = tuple(int(s) for s in re.shape[1:])
    m_total = 1
    for s in rest:
        m_total *= s
    tm = _stage_tile(m_total)
    out = _pair_call(
        n, k, m_total, tm,
        lambda i: (0, i), lambda i: (0, i),
        re.reshape(k, m_total), im.reshape(k, m_total),
        inverse, 1.0,
    )
    return out.reshape(*rest, 2, n)


def _stage_pair_auto(z, n: int, inverse: bool, scale: float, prec):
    """Fused pair kernel when eligible (f32, TPU, aligned), else the
    XLA pair-block dot."""
    k = int(z.shape[0])
    m = int(z.shape[-1])
    b = 1
    for s in z.shape[1:-2]:
        b *= int(s)
    if (
        z.dtype == jnp.float32
        and _use_fused_stage(k, b * m, n)
        and _stage_tile(m) is not None
    ):
        return _stage_pair_fused(z, n, inverse, scale)
    return _stage_pair(z, n, inverse, scale, prec)


# ----------------------------------------------------------------------
# Hermitian extension kernel (axis 0): out rows 0..m-1 copy the half
# spectrum, row m is the Nyquist plane, rows m+1..n-1 are the mirrored
# source row with both trailing axes index-mapped k -> (n-k) % n.
#
# The fused variant consumes the exit stage's RAW cat-dot outputs
# (zr, zi of shape (m, n1, 2*n2)) and performs the plane combine
# (re = zr[..., :n2] - zi[..., n2:], im = zr[..., n2:] + zi[..., :n2])
# inside VMEM — deleting the 3.2 GB combine pass the XLA stage pays
# (measured −3 ms at 512^3 on the bench v5e).
# ----------------------------------------------------------------------
def _ext_fused_kernel_factory(m: int, n2: int):
    from jax.experimental import pallas as pl

    def kern(p1_ref, p2_ref, zr_ref, zi_ref, nyr_ref, nyi_ref, ore_ref, oim_ref):
        p = pl.program_id(0)

        def combined():
            zr = zr_ref[0]
            zi = zi_ref[0]
            return zr[:, :n2] - zi[:, n2:], zr[:, n2:] + zi[:, :n2]

        @pl.when(p < m)
        def _():
            cre, cim = combined()
            ore_ref[0] = cre
            oim_ref[0] = cim

        @pl.when(p == m)
        def _():
            ore_ref[0] = nyr_ref[...]
            oim_ref[0] = nyi_ref[...]

        @pl.when(p > m)
        def _():
            pj = p1_ref[...]
            pk = p2_ref[...]

            def d(a, b):
                return jax.lax.dot_general(
                    a, b, ((((1,), (0,))), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            def revroll(s):
                hi = s.astype(jnp.bfloat16)
                lo = (s - hi.astype(jnp.float32)).astype(jnp.bfloat16)
                t_hi = d(hi, pk).astype(jnp.bfloat16)
                t_lo = d(lo, pk).astype(jnp.bfloat16)
                return d(pj, t_hi) + d(pj, t_lo)

            cre, cim = combined()
            ore_ref[0] = revroll(cre)
            oim_ref[0] = -revroll(cim)

    return kern


def _ext_fused_pallas(zr, zi, nyr, nyi):
    """Raw exit-dot planes (m, n1, 2*n2) + Nyquist -> full (2m, n1, n2)."""
    from jax.experimental import pallas as pl

    m, n1, n2t = (int(s) for s in zr.shape)
    n2 = n2t // 2
    n = 2 * m

    def src(pidx):
        return jnp.where(pidx < m, pidx, jnp.where(pidx == m, 0, n - pidx))

    return pl.pallas_call(
        _ext_fused_kernel_factory(m, n2),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((n1, n1), lambda p: (0, 0)),
            pl.BlockSpec((n2, n2), lambda p: (0, 0)),
            pl.BlockSpec((1, n1, 2 * n2), lambda p: (src(p), 0, 0)),
            pl.BlockSpec((1, n1, 2 * n2), lambda p: (src(p), 0, 0)),
            pl.BlockSpec((n1, n2), lambda p: (0, 0)),
            pl.BlockSpec((n1, n2), lambda p: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, n1, n2), lambda p: (p, 0, 0)),
            pl.BlockSpec((1, n1, n2), lambda p: (p, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, n1, n2), zr.dtype),
            jax.ShapeDtypeStruct((n, n1, n2), zi.dtype),
        ],
        interpret=jax.default_backend() != "tpu",
    )(_perm_bf(n1), _perm_bf(n2), zr, zi, nyr, nyi)


def _ext_xla(ere, eim, nyr, nyi):
    """XLA fallback extension (roll/rev/concat — pass-count-bound but
    portable; used on CPU and for shapes the kernel's tiles dislike)."""
    from ._planar import hermitian_upper

    m = int(ere.shape[0])
    return (
        jnp.concatenate([ere, nyr[None], hermitian_upper(ere, m - 1)], 0),
        jnp.concatenate([eim, nyi[None], -hermitian_upper(eim, m - 1)], 0),
    )


def _use_pallas_ext(n1: int, n2: int) -> bool:
    if os.environ.get("HEAT_TPU_FFT_EXT_PALLAS", "1") != "1":
        return False
    if jax.default_backend() != "tpu":
        return False
    # the kernel's bf16x2 MXU reversal is HIGH-class accuracy; a HIGHEST
    # run must not silently cap the mirrored upper half at ~2^-17
    if not _precision_is_high():
        return False
    # one (1, n1, n2) row block per step: keep the tiles exact
    return n1 % 8 == 0 and n2 % 128 == 0 and n1 >= 8 and n2 >= 128


def leading_eligible(re: jax.Array, axes, im_present: bool) -> bool:
    """2-D/3-D all-axes full-length f32/f64 transforms (f64 runs the
    hi/lo split contraction on TPU, native dots elsewhere); the real
    path (no im) additionally halves axis 0, so n0 must be even."""
    if os.environ.get("HEAT_TPU_FFT_LEADING", "1") != "1":
        return False
    nd = re.ndim
    if nd not in (2, 3) or len(axes) != nd:
        return False
    if re.dtype not in (jnp.float32, jnp.float64):
        return False
    if sorted(a % nd for a in axes) != list(range(nd)):
        return False
    if any(int(s) < 2 for s in re.shape):
        return False
    if not im_present and int(re.shape[0]) % 2 != 0:
        return False
    return True


def rfft3_leading(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """Full 3-D spectrum of a real (n0, n1, n2) array, all axes.

    Axis 0 is halved to m = n0//2 bins (the Nyquist bin rides a side
    chain), the three stages contract the leading dim in turn — the
    final stage lands the (k0, k1, k2) orientation with no transposes —
    and the Hermitian upper half is assembled by the extension kernel."""
    from ._planar import scale_factor

    n0, n1, n2 = (int(s) for s in x.shape)
    m = n0 // 2
    dt = str(x.dtype)
    prec = _precision()
    s = scale_factor([n0, n1, n2], norm, False)

    wc1 = _w_cat(n1, dt, False, 1.0)
    wc2 = _w_cat(n2, dt, False, float(s))  # norm folded into the exit
    if dt == "float32" and _use_fused_stage(n1, n2 * m, n1) and _stage_tile(m) is not None:
        # one cat entry dot (x read once) feeding the blocked mid kernel
        z = _dg0(x, _w_entry_cat(n0, m, dt), prec)  # (n1, n2, 2m)
        mre, mim = _stage_fused_pallas_blocked(z, n1, m, False, 1.0)
    else:
        re = _dg0(x, _w_entry_half(n0, m, dt, "re"), prec)  # (n1, n2, m)
        im = _dg0(x, _w_entry_half(n0, m, dt, "im"), prec)
        mre, mim = _stage_auto(re, im, n1, False, 1.0, prec)  # (n2, m, n1)
    fuse_ext = dt == "float32" and _use_pallas_ext(n1, n2)
    if fuse_ext:
        # leave the exit planes UNcombined — the extension kernel folds
        # the combine into its row pass (one fewer full-size HBM pass)
        zr2 = _dg0(mre, wc2, prec)  # (m, n1, 2n2)
        zi2 = _dg0(mim, wc2, prec)
    else:
        ere, eim = _stage_auto(mre, mim, n2, False, float(s), prec)  # (m, n1, n2)

    # Nyquist side chain: bin n0/2 of the axis-0 DFT is the alternating
    # sum, then an ordinary 2-D transform of that (real) plane
    alt = jnp.asarray(
        np.where(np.arange(n0) % 2 == 0, 1.0, -1.0).astype(dt)
    )
    # precision=prec: without it this dot runs at the DEFAULT (bf16-pass)
    # matmul policy on TPU, silently degrading the whole Nyquist plane
    # below the engine's requested precision class
    nyq = jnp.tensordot(alt, x, ((0,), (0,)), precision=prec)  # (n1, n2)
    a = _dg0(nyq, wc1, prec)  # (n2, 2n1)
    br = _dg0(a[:, :n1], wc2, prec)  # (n1, 2n2)
    bi = _dg0(a[:, n1:], wc2, prec)
    nyr = br[:, :n2] - bi[:, n2:]
    nyi = br[:, n2:] + bi[:, :n2]

    if fuse_ext:
        return _ext_fused_pallas(zr2, zi2, nyr, nyi)
    return _ext_xla(ere, eim, nyr, nyi)


def rfft2_leading(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """Full 2-D spectrum of a real (n0, n1) array, both axes: axis 0 is
    halved to m = n0//2 bins through the cat entry dot, the single mid
    stage runs pair-block, the Nyquist bin rides the alternating-sum
    side chain and the Hermitian upper half is the 2-D rev-roll mirror
    (XLA — at one (m, n1) plane the extension is too small to
    kernelize)."""
    from ._planar import scale_factor

    n0, n1 = (int(s) for s in x.shape)
    m = n0 // 2
    dt = str(x.dtype)
    prec = _precision()
    s = scale_factor([n0, n1], norm, False)

    z = _dg0(x, _w_entry_cat(n0, m, dt), prec)  # (n1, 2m)
    z = z.reshape(n1, 2, m)
    z = _stage_pair_auto(z, n1, False, float(s), prec)  # (m, 2, k1)
    ere = z[..., 0, :]
    eim = z[..., 1, :]

    # Nyquist side chain: bin n0/2 is the alternating sum, then one 1-D
    # DFT over the remaining axis (see rfft3_leading on the precision)
    alt = jnp.asarray(
        np.where(np.arange(n0) % 2 == 0, 1.0, -1.0).astype(dt)
    )
    nyq = jnp.tensordot(alt, x, ((0,), (0,)), precision=prec)  # (n1,)
    a = _dg0(nyq, _w_cat(n1, dt, False, float(s)), prec)  # (2n1,)
    nyr = a[:n1]
    nyi = a[n1:]

    def upper(p):
        return jax.lax.rev(jnp.roll(p[1:m], -1, 1), (0, 1))

    return (
        jnp.concatenate([ere, nyr[None], upper(ere)], 0),
        jnp.concatenate([eim, nyi[None], -upper(eim)], 0),
    )


def cfftn_leading(
    re: jax.Array, im: jax.Array, inverse: bool, norm
) -> Tuple[jax.Array, jax.Array]:
    """Full 2-D/3-D transform of a complex plane pair, all axes.

    The entry contracts axis 0 with the ``[W_re|W_im]`` / ``[-W_im|W_re]``
    cat pair (re read once, im read once, no combine pass) and lands the
    pair-block layout; every later axis is ONE pair-block stage — the
    plane pair shares a single relayout per stage where the
    separate-plane engine paid two, which is the measured complex-vs-
    real gap (38.9 ms vs 18.5 at 512^3) this path closes.  Norm is
    folded into the last stage's matrix."""
    from ._planar import scale_factor

    nd = re.ndim
    shape = tuple(int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _precision()
    s = scale_factor(list(shape), norm, inverse)

    n0 = shape[0]
    if re.dtype == jnp.float32 and _use_fused_stage(
        n0, int(np.prod(shape[1:], dtype=np.int64)), n0
    ):
        z = _entry_pair_fused(re, im, n0, inverse)  # (*rest, 2, n0)
    else:
        z = _dg0(re, _w_cat(n0, dt, inverse, 1.0), prec) + _dg0(
            im, _w_cat_im(n0, dt, inverse, 1.0), prec
        )  # (*rest, 2n0) cat layout
        z = z.reshape(*shape[1:], 2, n0)
    for ax in range(1, nd):
        sc = float(s) if ax == nd - 1 else 1.0
        z = _stage_pair_auto(z, shape[ax], inverse, sc, prec)
    return z[..., 0, :], z[..., 1, :]


def cfft3_leading(
    re: jax.Array, im: jax.Array, inverse: bool, norm
) -> Tuple[jax.Array, jax.Array]:
    """3-D wrapper kept for the dispatch surface's historical name."""
    return cfftn_leading(re, im, inverse, norm)
