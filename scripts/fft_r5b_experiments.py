"""R5 FFT experiments, round B: merged-minor interleaved representation.

Round A failed spectacularly: any materialized tensor with a trailing
dim of 2 gets the TPU tile (8, 128) on its last two dims, padding 2->128
— a 64x memory/traffic blow-up (the compiler refused a 64 GB alloc for
f32[512,512,512,2]).  So the complex pair must live INSIDE the minor
dim: z[..., 2k+c] (interleaved), every DFT stage is a plain matmul
``(..., 2n) @ (2n, 2n)`` with the real 2x2-block DFT matrix, and moving
the transform to another axis is an explicit "swap-last-two" relayout
(A, B, 2C) -> (A, C, 2B) whose implementations this script races:

* swap_t: reshape/transpose/reshape (XLA fuses or it dies by tiling)
* swap_p: one per-row gather through a host-precomputed permutation

Chain for rfftn-3d (x real (S,S,S)):
  pass Z (plain matmul, real-in W) -> (X, Y, 2Kz), slice to 2m
  swap -> (X, m, 2Y); pass Y -> (X, m, 2Ky)
  leading transpose (m, X, 2Ky); swap -> (m, Ky, 2X); pass X -> (m, Ky, 2Kx)
  Hermitian extension + unstack + axis restore in ONE gather per plane.
"""

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from fft_r5_experiments import PREC, _wc, w2_full, w2_real_in, measure, accuracy


def swap_t(z, B, C):
    """(A, B, 2C) -> (A, C, 2B) via transpose."""
    A = z.shape[0]
    return z.reshape(A, B, C, 2).swapaxes(1, 2).reshape(A, C, 2 * B)


@functools.lru_cache(maxsize=32)
def _swap_perm(B, C):
    b, c, d = np.meshgrid(np.arange(B), np.arange(C), np.arange(2), indexing="ij")
    # out position (c, 2b+d) <- in position (b, 2c+d)
    perm = np.empty(B * 2 * C, np.int32)
    perm[c.ravel() * 2 * B + 2 * b.ravel() + d.ravel()] = (
        b.ravel() * 2 * C + 2 * c.ravel() + d.ravel()
    )
    return perm


def swap_p(z, B, C):
    A = z.shape[0]
    perm = jnp.asarray(_swap_perm(B, C))
    return jnp.take(z.reshape(A, B * 2 * C), perm, axis=1).reshape(A, C, 2 * B)


def _final_planes(z, S, m):
    """z (m, Ky, 2Kx) -> (re, im) planes (S, S, S) with Hermitian
    extension along the original Z axis, one fused gather per plane.

    full[x, y, k] = z[k, y, x] for k < m; conj(z[S-k, rev(y), rev(x)]) above.
    """
    kz = np.arange(S)
    lower = kz < m
    src_k = np.where(lower, kz, S - kz)
    rev = np.concatenate([[0], np.arange(S - 1, 0, -1)])
    ix = np.arange(S)
    # build index arrays for out[x, y, k]
    K = src_k[None, None, :]
    Y = np.where(lower[None, None, :], ix[None, :, None], rev[None, :, None])
    X = np.where(lower[None, None, :], ix[:, None, None], rev[:, None, None])
    sgn = np.where(lower, 1.0, -1.0).astype(np.float32)[None, None, :]
    zK, zY, zX = jnp.asarray(K), jnp.asarray(Y), jnp.asarray(X)
    re = z[zK, zY, 2 * zX]
    im = z[zK, zY, 2 * zX + 1] * jnp.asarray(sgn)
    return re, im


def make_merged(prec_name, swap):
    prec = PREC[prec_name]

    def run(x):
        S = x.shape[0]
        m = S // 2 + 1
        dt = str(x.dtype)
        Wr = jnp.asarray(w2_real_in(S, False, dt))
        W2 = jnp.asarray(w2_full(S, False, dt))
        mm = lambda a, w: jax.lax.dot_general(
            a.reshape(-1, a.shape[-1]), w, (((1,), (0,)), ((), ())), precision=prec
        ).reshape(*a.shape[:-1], w.shape[1])
        z = mm(x, Wr)  # (X, Y, 2S)
        z = z[:, :, : 2 * m]  # minor slice keeps (k, c) pairs
        z = swap(z, S, m)  # (X, m, 2Y)
        z = mm(z, W2)  # (X, m, 2Ky)
        z = jnp.swapaxes(z, 0, 1)  # (m, X, 2Ky) leading transpose
        z = swap(z, S, S)  # (m, Ky, 2X)
        z = mm(z, W2)  # (m, Ky, 2Kx)
        return _final_planes(z, S, m)

    return run


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None
    cands = {
        "m_swapT_high": make_merged("high", swap_t),
        "m_swapP_high": make_merged("high", swap_p),
        "m_swapT_highest": make_merged("highest", swap_t),
        "m_swapT_default": make_merged("default", swap_t),
    }
    n = 512 ** 3
    for name, fn in cands.items():
        if only and only not in name:
            continue
        try:
            rel = accuracy(fn)
            gb, sec = measure(fn)
            print(
                json.dumps(
                    {
                        "cand": name,
                        "rel_err_128": float(f"{rel:.3g}"),
                        "bytes_gb_512": round(gb, 2),
                        "sec_512": round(sec, 4),
                        "nominal_gflops": round(5.0 * n * np.log2(n) / sec / 1e9, 1),
                        "pct_bw_minimal": round(100 * 6.44 / 652.8 / sec, 1),
                    }
                ),
                flush=True,
            )
        except Exception as e:
            print(json.dumps({"cand": name, "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)


if __name__ == "__main__":
    main()
