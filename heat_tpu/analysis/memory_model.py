"""Static peak-HBM estimator: predict a program's memory before XLA does.

An OOM on a TPU pod surfaces as a mid-fit crash *after* minutes of
compile; this module predicts an executable's peak device-memory
footprint from its **jaxpr alone** — shapes x dtypes, a last-use
liveness walk, donation aliasing, and per-device division from the
operand shardings — so an over-budget program is a diagnostic
(**J301**) before the first byte of HLO exists.

The model (deliberately simple, cross-checked against
``Compiled.memory_analysis()`` in tests — within 10% on the real
kernels the suite pins):

* program inputs and constants are resident for the whole program
  (caller-owned; XLA cannot reuse them) **unless donated**;
* each eqn allocates its outputs, then frees operands whose last use
  this was — peak is read *between* those two steps, like a real
  allocator holding inputs and outputs simultaneously;
* an output may **reuse** the buffer of an operand dying at the same
  eqn when it fits (XLA's in-place elementwise/fusion reuse): a chain
  ``a*b+c`` costs one intermediate, not two;
* a donated input aliases the first same-shape/dtype output
  (``input_output_alias``), making that output allocation free;
* a sharded operand costs its **per-device shard** bytes
  (``sharding.shard_shape``); intermediates inherit the division factor
  of their largest live operand (GSPMD keeps the split through
  elementwise/reduce chains — the cases the dispatch layer compiles).

``HEAT_TPU_HBM_BUDGET_BYTES`` (> 0) arms the budget check: the dispatch
compile hook emits J301 whenever a fresh executable's predicted
per-device peak exceeds it, surfaced through the ``Diagnostic`` ring,
``analysis.diags.J301``, ``/statusz`` and flight-recorder bundles like
every other finding.  The latest estimates are kept in a bounded table
(:func:`peak_summary`) read by the introspection surfaces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import _env
from ..telemetry import metrics as _tm
from . import tsan as _tsan
from .diagnostics import Diagnostic

__all__ = [
    "PeakEstimate",
    "check_budget",
    "estimate_jaxpr_peak",
    "estimate_peak",
    "hbm_budget_bytes",
    "note_estimate",
    "peak_summary",
    "predicted_peak_bytes",
    "reset_estimates",
    "shard_shapes_of",
]


@dataclass
class PeakEstimate:
    """One program's predicted memory footprint (bytes).

    ``peak_bytes`` is the global (all-shards-summed) liveness peak;
    ``per_device_bytes`` divides each buffer by its modeled shard count
    — the number a single chip's HBM must hold and the one J301 checks.
    ``argument_bytes``/``output_bytes``/``temp_bytes`` decompose the
    per-device peak the way ``Compiled.memory_analysis()`` reports its
    own (arguments + outputs + temporaries), for cross-checking."""

    peak_bytes: int = 0
    per_device_bytes: int = 0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    aliased_bytes: int = 0
    n_eqns: int = 0
    details: Dict[str, Any] = field(default_factory=dict)


def hbm_budget_bytes() -> int:
    """The armed per-device HBM budget (0 = check off)."""
    return _env.env_int("HEAT_TPU_HBM_BUDGET_BYTES")


def _nbytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dt = getattr(aval, "dtype", None)
    if shape is None or dt is None:
        return 0
    n = 1
    for s in shape:
        try:
            n *= int(s)
        except TypeError:  # pragma: no cover - symbolic dims
            return 0
    try:
        return n * np.dtype(dt).itemsize
    except TypeError:  # pragma: no cover
        return 0


def _shard_factor(var, shard_shape) -> float:
    """global bytes / per-device bytes for one invar (>= 1.0)."""
    if shard_shape is None:
        return 1.0
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 1.0
    g = 1
    for s in shape:
        g *= int(s)
    l = 1
    for s in shard_shape:
        l *= int(s)
    if l <= 0 or g <= 0:
        return 1.0
    return max(1.0, g / l)


class _Lit:
    """Wrapper giving literal operands identity-keyed liveness slots."""

    __slots__ = ("aval",)

    def __init__(self, aval):
        self.aval = aval


def _unwrap(jaxpr):
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    while (
        len(jaxpr.eqns) == 1
        and jaxpr.eqns[0].primitive.name == "pjit"
        and jaxpr.eqns[0].params.get("jaxpr") is not None
    ):
        jaxpr = getattr(jaxpr.eqns[0].params["jaxpr"], "jaxpr",
                        jaxpr.eqns[0].params["jaxpr"])
    return jaxpr


def estimate_jaxpr_peak(
    jaxpr,
    donate_argnums: Sequence[int] = (),
    shard_shapes: Optional[Sequence] = None,
    label: str = "program",
) -> PeakEstimate:
    """Liveness-walk one (Closed)Jaxpr and return its
    :class:`PeakEstimate`.

    ``shard_shapes`` is an optional per-invar list of per-device shard
    shapes (``sharding.shard_shape(global_shape)``; None entries =
    replicated) — the per-device division of the mesh the program will
    run under."""
    jaxpr = _unwrap(jaxpr)
    invars = list(jaxpr.invars)
    constvars = list(jaxpr.constvars)
    n_in = len(invars)
    if shard_shapes is None:
        shard_shapes = [None] * n_in
    shard_shapes = list(shard_shapes) + [None] * (n_in - len(shard_shapes))

    factors: Dict[int, float] = {}
    for v, ss in zip(invars, shard_shapes):
        factors[id(v)] = _shard_factor(v, ss)
    for v in constvars:
        factors[id(v)] = 1.0

    # last textual use per var id; program outputs (and their aliases)
    # are pinned past the last eqn
    last_use: Dict[int, int] = {}
    eqns = list(jaxpr.eqns)
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "aval") or type(v).__name__ == "Literal":
                continue
            last_use[id(v)] = i
    pinned = {id(v) for v in invars} | {id(v) for v in constvars}
    out_ids = {id(v) for v in jaxpr.outvars if hasattr(v, "aval")}

    # donation: greedy-match each donated invar to the first unclaimed
    # program output of identical shape+dtype (XLA's input_output_alias)
    donated = set()
    alias_out: Dict[int, int] = {}  # outvar id -> aliased invar id
    claimed = set()
    aliased_bytes = 0
    for argnum in donate_argnums:
        if not (0 <= int(argnum) < n_in):
            continue
        iv = invars[int(argnum)]
        key = (getattr(iv.aval, "shape", None), getattr(iv.aval, "dtype", None))
        for ov in jaxpr.outvars:
            if id(ov) in claimed or not hasattr(ov, "aval"):
                continue
            if (getattr(ov.aval, "shape", None),
                    getattr(ov.aval, "dtype", None)) == key:
                claimed.add(id(ov))
                alias_out[id(ov)] = id(iv)
                donated.add(id(iv))
                aliased_bytes += _nbytes(iv)
                break

    arg_bytes_g = sum(_nbytes(v) for v in invars + constvars)
    arg_bytes_d = sum(
        _nbytes(v) / factors[id(v)] for v in invars + constvars
    )
    out_bytes_d = 0.0
    live: Dict[int, Tuple[float, float]] = {}  # id -> (global, per-device)
    for v in invars + constvars:
        b = _nbytes(v)
        live[id(v)] = (b, b / factors[id(v)])

    cur_g = float(arg_bytes_g)
    cur_d = float(arg_bytes_d)
    peak_g, peak_d = cur_g, cur_d

    for i, eqn in enumerate(eqns):
        in_ids = [
            id(v) for v in eqn.invars
            if hasattr(v, "aval") and type(v).__name__ != "Literal"
        ]
        # intermediates inherit the division of their largest live operand
        op_factor = 1.0
        best = -1.0
        for vid in in_ids:
            g, d = live.get(vid, (0.0, 0.0))
            if g > best:
                best = g
                op_factor = (g / d) if d > 0 else 1.0

        dying = [
            vid for vid in set(in_ids)
            if last_use.get(vid) == i
            and vid not in out_ids
            and (vid not in pinned or vid in donated)
        ]
        reusable = sorted(
            (live.get(vid, (0.0, 0.0))[0] for vid in dying), reverse=True
        )

        alloc_g = alloc_d = 0.0
        for ov in eqn.outvars:
            b = float(_nbytes(ov))
            if id(ov) in alias_out:
                # aliased output lives in the donated input's buffer
                src = alias_out[id(ov)]
                live[id(ov)] = live.get(src, (b, b / op_factor))
                continue
            if reusable and reusable[0] >= b > 0:
                # in-place reuse of a dying operand's buffer
                reusable[0] -= b
                reusable.sort(reverse=True)
                live[id(ov)] = (b, b / op_factor)
                continue
            alloc_g += b
            alloc_d += b / op_factor
            live[id(ov)] = (b, b / op_factor)

        cur_g += alloc_g
        cur_d += alloc_d
        peak_g = max(peak_g, cur_g)
        peak_d = max(peak_d, cur_d)

        for vid in dying:
            g, d = live.pop(vid, (0.0, 0.0))
            cur_g -= g
            cur_d -= d

    for ov in jaxpr.outvars:
        if hasattr(ov, "aval") and id(ov) not in alias_out:
            b = float(_nbytes(ov))
            out_bytes_d += live.get(id(ov), (b, b))[1]

    temp_d = max(0.0, peak_d - arg_bytes_d - out_bytes_d)
    return PeakEstimate(
        peak_bytes=int(peak_g),
        per_device_bytes=int(peak_d),
        argument_bytes=int(arg_bytes_d),
        output_bytes=int(out_bytes_d),
        temp_bytes=int(temp_d),
        aliased_bytes=int(aliased_bytes),
        n_eqns=len(eqns),
        details={"label": label},
    )


def shard_shapes_of(leaves: Sequence) -> List[Optional[Tuple[int, ...]]]:
    """Per-device shard shapes of concrete argument leaves (None =
    replicated / shardingless) — the per-invar division list
    :func:`estimate_jaxpr_peak` consumes."""
    out: List[Optional[Tuple[int, ...]]] = []
    for leaf in leaves:
        ss = None
        sharding = getattr(leaf, "sharding", None)
        shape = getattr(leaf, "shape", None)
        if sharding is not None and shape is not None:
            try:
                ss = tuple(sharding.shard_shape(tuple(shape)))
            except Exception:  # lint: allow H501(sharding probe is best-effort; replicated assumed)
                ss = None
        out.append(ss)
    return out


def estimate_peak(
    fn,
    *args,
    donate_argnums: Sequence[int] = (),
    label: Optional[str] = None,
    **kwargs,
) -> PeakEstimate:
    """Trace ``fn(*args, **kwargs)`` and estimate its peak footprint.

    Per-device division comes from the arguments' live shardings
    (``.sharding.shard_shape``) where present.  Tracing only — the
    program is never compiled or executed."""
    if label is None:
        label = getattr(fn, "__name__", None) or type(fn).__name__
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return estimate_jaxpr_peak(
        jaxpr, donate_argnums=donate_argnums,
        shard_shapes=shard_shapes_of(jax.tree_util.tree_leaves(args)),
        label=label,
    )


def check_budget(est: PeakEstimate, label: str = "program") -> Optional[Diagnostic]:
    """The J301 verdict for one estimate against
    ``HEAT_TPU_HBM_BUDGET_BYTES`` (None when under budget / unarmed)."""
    budget = hbm_budget_bytes()
    if budget <= 0 or est.per_device_bytes <= budget:
        return None
    return Diagnostic(
        rule="J301",
        message=(
            f"predicted per-device peak {est.per_device_bytes:,} B exceeds "
            f"the HBM budget {budget:,} B "
            f"(args {est.argument_bytes:,} + out {est.output_bytes:,} + "
            f"temps {est.temp_bytes:,}) — an OOM caught before compile; "
            "shard the dominant operand, donate the dead buffer, or chunk "
            "the computation"
        ),
        location=label,
        details={
            "predicted_peak_bytes": est.per_device_bytes,
            "budget_bytes": budget,
            "argument_bytes": est.argument_bytes,
            "output_bytes": est.output_bytes,
            "temp_bytes": est.temp_bytes,
        },
    )


# ----------------------------------------------------------------------
# introspection: the latest estimates, bounded, for /statusz + bundles
# ----------------------------------------------------------------------
_ESTIMATES: "Dict[str, dict]" = {}
_EST_LOCK = _tsan.register_lock("analysis.memory_model.estimates")
_EST_MAX = 256

_PEAK_G = _tm.gauge(
    "analysis.hbm_predicted_peak_bytes",
    "latest statically predicted per-device peak HBM of a compiled program",
)
_EST_C = _tm.counter(
    "analysis.hbm_estimates", "programs walked by the static peak-HBM estimator"
)


def note_estimate(label: str, est: PeakEstimate) -> None:
    """Record one estimate into the bounded introspection table and the
    telemetry gauges (the dispatch-hook path calls this per miss)."""
    _EST_C.inc()
    _PEAK_G.set(float(est.per_device_bytes))
    with _EST_LOCK:
        _tsan.note_access("analysis.memory_model.estimates")
        if len(_ESTIMATES) >= _EST_MAX:
            _ESTIMATES.clear()
        _ESTIMATES[str(label)[:200]] = {
            "per_device_bytes": est.per_device_bytes,
            "peak_bytes": est.peak_bytes,
            "argument_bytes": est.argument_bytes,
            "output_bytes": est.output_bytes,
            "temp_bytes": est.temp_bytes,
            "n_eqns": est.n_eqns,
        }


def predicted_peak_bytes() -> int:
    """The worst (largest) per-device peak across the recorded
    estimates — the static prediction the runtime observatory's HBM
    watermark cross-checks its *measured* bytes against (0 before any
    program was walked).  The ``analysis.hbm_predicted_peak_bytes``
    gauge tracks only the LATEST estimate; the cross-check wants the
    worst one still live in the table."""
    with _EST_LOCK:
        _tsan.note_access("analysis.memory_model.estimates", write=False)
        if not _ESTIMATES:
            return 0
        return max(int(e["per_device_bytes"]) for e in _ESTIMATES.values())


def peak_summary() -> Dict[str, Any]:
    """The bounded per-program estimate table plus the armed budget —
    the ``analysis`` section /statusz and crash bundles embed."""
    with _EST_LOCK:
        _tsan.note_access("analysis.memory_model.estimates", write=False)
        per = dict(_ESTIMATES)
    return {
        "budget_bytes": hbm_budget_bytes(),
        "estimates": per,
    }


def reset_estimates() -> None:
    """Drop the recorded estimates (tests)."""
    with _EST_LOCK:
        _tsan.note_access("analysis.memory_model.estimates")
        _ESTIMATES.clear()
