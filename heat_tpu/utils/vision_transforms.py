"""Image transforms, analog of heat/utils/vision_transforms.py.

The reference is a passthrough to ``torchvision.transforms`` (reference
vision_transforms.py:10-19).  The TPU-native build provides jnp-backed
implementations of the common transforms (so pipelines run without torch
and compose with jax arrays / DNDarrays), and falls back to torchvision
for anything not implemented here — the same ``__getattr__`` contract.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

__all__ = [
    "CenterCrop",
    "Compose",
    "Lambda",
    "Normalize",
    "RandomHorizontalFlip",
    "ToTensor",
]


def _as_jnp(pic):
    from ..core.dndarray import DNDarray

    if isinstance(pic, DNDarray):
        return pic._dense()
    return jnp.asarray(np.asarray(pic))


class Compose:
    """Chain transforms (torchvision.transforms.Compose contract)."""

    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, pic):
        for t in self.transforms:
            pic = t(pic)
        return pic

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"{type(self).__name__}([{inner}])"


class ToTensor:
    """HWC uint8 [0, 255] -> CHW float32 [0, 1] (torchvision semantics)."""

    def __call__(self, pic):
        arr = _as_jnp(pic)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3:
            arr = jnp.transpose(arr, (2, 0, 1))
        if jnp.issubdtype(arr.dtype, jnp.integer):
            arr = arr.astype(jnp.float32) / 255.0
        return arr.astype(jnp.float32)

    def __repr__(self):
        return "ToTensor()"


class Normalize:
    """Channel-wise (x - mean) / std on CHW arrays."""

    def __init__(self, mean, std, inplace: bool = False):
        self.mean = jnp.asarray(mean, jnp.float32)
        self.std = jnp.asarray(std, jnp.float32)

    def __call__(self, pic):
        arr = _as_jnp(pic)
        shape = (-1,) + (1,) * (arr.ndim - 1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)

    def __repr__(self):
        return f"Normalize(mean={self.mean.tolist()}, std={self.std.tolist()})"


class CenterCrop:
    """Crop the central (h, w) window of a (..., H, W) array."""

    def __init__(self, size):
        self.size = (int(size), int(size)) if np.isscalar(size) else tuple(size)

    def __call__(self, pic):
        arr = _as_jnp(pic)
        h, w = self.size
        H, W = arr.shape[-2], arr.shape[-1]
        top, left = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return arr[..., top : top + h, left : left + w]

    def __repr__(self):
        return f"CenterCrop(size={self.size})"


class RandomHorizontalFlip:
    """Flip the width axis with probability p (host RNG — transforms run in
    the input pipeline, not inside jit).

    Width-axis inference follows torchvision: 3-D input that is not
    channel-first (i.e. HWC, the PIL/numpy layout before ToTensor) flips
    axis=-2; CHW tensors and 2-D grayscale flip axis=-1.
    """

    def __init__(self, p: float = 0.5, rng: np.random.Generator | None = None):
        self.p = float(p)
        self.rng = rng or np.random.default_rng()

    @staticmethod
    def _width_axis(arr) -> int:
        if arr.ndim >= 3 and arr.shape[-1] in (1, 3, 4) and arr.shape[-3] not in (1, 3, 4):
            return -2  # HWC: last axis is channels, width is -2
        return -1

    def __call__(self, pic):
        arr = _as_jnp(pic)
        if self.rng.random() < self.p:
            return jnp.flip(arr, axis=self._width_axis(arr))
        return arr

    def __repr__(self):
        return f"RandomHorizontalFlip(p={self.p})"


class Lambda:
    """Wrap a user callable."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, pic):
        return self.fn(pic)

    def __repr__(self):
        return "Lambda()"


def __getattr__(name):
    """Fall back to torchvision.transforms for anything not implemented,
    mirroring the reference's passthrough (vision_transforms.py:10-19)."""
    try:
        import torchvision.transforms as tvt
    except Exception as exc:  # pragma: no cover - torchvision always bundled
        raise AttributeError(f"module {name} not implemented in heat_tpu") from exc
    if hasattr(tvt, name):
        return getattr(tvt, name)
    raise AttributeError(f"module {name} not implemented in torchvision or heat_tpu")
