"""DP-SGD training-throughput benchmark (BASELINE config 4: the
examples/nn MNIST CNN under data parallelism; the reference measures the
same workload through perun in its DASO/DataParallel examples)."""

from monitor import RESULTS, monitor


def run_nn_benchmarks(scale: float = 1.0) -> None:
    import jax
    import optax

    import heat_tpu as ht
    from heat_tpu.utils.data import synthetic_mnist

    n = max(int(2048 * scale), 256)
    batch = 128

    x, y = synthetic_mnist(n)

    import flax.linen as lnn

    class CNN(lnn.Module):
        @lnn.compact
        def __call__(self, t):
            t = lnn.Conv(16, (3, 3))(t)
            t = lnn.relu(t)
            t = lnn.avg_pool(t, (2, 2), strides=(2, 2))
            t = t.reshape((t.shape[0], -1))
            t = lnn.Dense(64)(t)
            t = lnn.relu(t)
            return lnn.Dense(10)(t)

    dp = ht.nn.DataParallel(CNN(), optimizer=optax.adam(1e-3))
    xb0 = ht.array(x.numpy()[:batch], split=0)
    dp.init(jax.random.PRNGKey(0), xb0)

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    xd, yd = x.numpy(), y.numpy()
    # warmup/compile one step
    dp.step(loss_fn, ht.array(xd[:batch], split=0), ht.array(yd[:batch], split=0))

    @monitor()
    def dp_sgd_epoch():
        losses = []
        for start in range(0, n - batch + 1, batch):
            xb = ht.array(xd[start : start + batch], split=0)
            yb = ht.array(yd[start : start + batch], split=0)
            losses.append(dp.step(loss_fn, xb, yb))
        return losses[-1]

    dp_sgd_epoch()
    elapsed = RESULTS[-1]["seconds"]
    steps = n // batch
    RESULTS[-1]["steps_per_s"] = round(steps / max(elapsed, 1e-9), 2)
    print(f'# dp_sgd: {RESULTS[-1]["steps_per_s"]} steps/s at batch {batch}')
