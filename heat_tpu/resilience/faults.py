"""Deterministic, seeded fault injection for failure-path testing.

Long-running fits on preemptible TPU pods see transient IO errors, host
preemption and compile failures; CPU CI sees none of them.  This module
makes failure a *scriptable, reproducible* scenario: named injection
points (``faults.inject("io.write", path=...)``) are wired through the
communication, dispatch, io and checkpoint layers, and a **fault plan**
decides, per site and per call index, whether a scripted fault fires.

Sites may be evaluated from *any* thread — the overlap layer's
``checkpoint.async_write`` (and the ``checkpoint.save``/
``checkpoint.write`` sites under an async save) fire on the background
writer thread, which is how kill-mid-async-write scenarios are
scripted; the injector is lock-protected, so per-site call indices stay
deterministic across threads as long as the call *sequence* is.

Plan format
-----------
A plan is a mapping from site pattern to a list of rules::

    {
        "io.write":          [0, 3],                    # transient at call 0 and 3
        "dispatch.compile":  [{"at": 1, "kind": "transient"}],
        "checkpoint.save":   [{"at": 2, "kind": "kill"}],
        "comm.*":            [{"p": 0.01, "kind": "transient"}],
    }

* Site patterns match exactly or by :mod:`fnmatch` glob (``"io.*"``).
* A bare int ``n`` is shorthand for ``{"at": n, "kind": "transient"}``.
* ``at`` may be an int or list of ints — the per-site **call index** at
  which the rule fires (each evaluated injection point increments the
  site's counter).
* ``p`` fires with probability ``p`` per call, driven by a
  ``random.Random`` seeded from ``(seed, site)`` — the same plan + seed
  + call sequence always injects the same faults.
* ``kind``: ``"transient"`` (raises :class:`TransientFault`, retryable),
  ``"permanent"`` (raises :class:`PermanentFault`, never retried) or
  ``"kill"`` (``os._exit`` — simulated host preemption; exit code via
  ``exit_code``, default 137).
* ``times`` caps how often a ``p`` rule may fire (default unlimited;
  ``at`` rules fire once per listed index).

Activation
----------
* Context manager: ``with fault_plan({...}, seed=0) as inj: ...`` —
  ``inj.hits``/``inj.injected`` hold per-site counters for assertions.
* Environment: ``HEAT_TPU_FAULT_PLAN`` holds either inline JSON or a
  path to a JSON file (``{"plan": {...}, "seed": 0}`` or just the plan
  mapping).  This is how a *subprocess* under test gets its script —
  e.g. "kill the fit at iteration k" for kill-and-resume tests.

With no active plan, :func:`inject` is a counter-free no-op — the
injection points cost one global read on production paths.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional

from .errors import PermanentFault, TransientFault
from ..analysis import tsan as _tsan
from ..telemetry import metrics as _tm

__all__ = [
    "FaultInjector",
    "KNOWN_SITES",
    "fault_plan",
    "inject",
    "active_injector",
    "fault_stats",
    "reset_fault_stats",
    "refresh_env_plan",
]

PLAN_ENV = "HEAT_TPU_FAULT_PLAN"

#: Registry of every named injection point wired through the stack.  A
#: fault plan targeting a site not listed here can never fire; the AST
#: linter's H302 rule (heat_tpu/analysis/ast_lint.py) statically checks
#: each ``inject("...")`` / ``fault_site=...`` literal in the sources
#: against this table, so the registry and the wiring cannot drift.
#: PURE LITERAL — the linter parses this assignment without importing.
KNOWN_SITES = (
    "comm.init",
    "comm.collective",
    "dispatch.compile",
    "io.open",
    "io.write",
    "checkpoint.save",
    "checkpoint.restore",
    "checkpoint.write",
    "checkpoint.async_write",
    "estimator.iter",
    "kmeans.iter",
    "kmedians.iter",
    "kmedoids.iter",
    "lasso.iter",
    "pca.stage",
    "elastic.detect",
    "elastic.reshape",
    "elastic.resume",
    "serve.load",
    "serve.predict",
    "serve.batch",
    "serve.shadow",
    "aot.load",
    "aot.save",
    "fleet.route",
    "fleet.spawn",
    "stream.read",
    "stream.commit",
    "stream.refresh",
    "qos.preempt",
)

#: process-lifetime totals (survive injector deactivation) — registered
#: in the shared telemetry registry as ``fault.*``; the bench resilience
#: record and ``telemetry.snapshot()`` both read them
_SITES_EVALUATED = _tm.counter("fault.sites_evaluated")
_FAULTS_INJECTED = _tm.counter("fault.faults_injected")


def _normalize_rule(rule: Any) -> Dict:
    if isinstance(rule, int):
        rule = {"at": rule}
    if not isinstance(rule, dict):
        raise TypeError(f"fault rule must be an int or dict, got {type(rule)}")
    out = dict(rule)
    kind = out.setdefault("kind", "transient")
    if kind not in ("transient", "permanent", "kill"):
        raise ValueError(f"unknown fault kind {kind!r}")
    if "at" in out:
        at = out["at"]
        out["at"] = frozenset([int(at)] if isinstance(at, int) else [int(i) for i in at])
    elif "p" not in out:
        raise ValueError("fault rule needs 'at' or 'p'")
    if "p" in out:
        p = float(out["p"])
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault probability must be in [0, 1], got {p}")
        out["p"] = p
    return out


class FaultInjector:
    """An activated fault plan with per-site hit accounting.

    ``hits[site]`` counts every evaluation of the site's injection
    point; ``injected[site]`` lists ``(call_index, kind)`` for each
    fault actually raised — the assertion surface of failure tests.
    """

    def __init__(self, plan: Dict[str, Any], seed: int = 0):
        self.seed = int(seed)
        self.plan = {
            site: [_normalize_rule(r) for r in (rules if isinstance(rules, list) else [rules])]
            for site, rules in (plan or {}).items()
        }
        self.hits: Dict[str, int] = {}
        self.injected: Dict[str, List] = {}
        self._fired: Dict[int, int] = {}  # id(rule) -> times fired
        self._rngs: Dict[str, random.Random] = {}
        # sites fire from the async-writer and loader threads; the
        # registered lock keeps per-site call indices deterministic and
        # lets the sanitizer verify every evaluation holds it
        self._lock = _tsan.register_lock("resilience.faults.injector")
        self._prev: Optional["FaultInjector"] = None

    # -- plan evaluation ------------------------------------------------
    def _rules_for(self, site: str) -> List[Dict]:
        rules = self.plan.get(site)
        if rules is not None:
            return rules
        out: List[Dict] = []
        for pattern, rs in self.plan.items():
            if "*" in pattern or "?" in pattern or "[" in pattern:
                if fnmatch.fnmatchcase(site, pattern):
                    out.extend(rs)
        return out

    def check(self, site: str, info: Dict) -> None:
        """Record one evaluation of ``site`` and raise if the plan says so."""
        with self._lock:
            _tsan.note_access("resilience.faults.counters")
            index = self.hits.get(site, 0)
            self.hits[site] = index + 1
            _SITES_EVALUATED.inc()
            fire_kind = None
            for rule in self._rules_for(site):
                fired = self._fired.get(id(rule), 0)
                times = rule.get("times")
                if times is not None and fired >= times:
                    continue
                hit = False
                if "at" in rule and index in rule["at"]:
                    hit = True
                elif "p" in rule:
                    rng = self._rngs.get(site)
                    if rng is None:
                        rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
                    hit = rng.random() < rule["p"]
                if hit:
                    self._fired[id(rule)] = fired + 1
                    fire_kind = rule["kind"]
                    break
            if fire_kind is None:
                return
            self.injected.setdefault(site, []).append((index, fire_kind))
            _FAULTS_INJECTED.inc()
        if fire_kind == "kill":
            os._exit(int(rule.get("exit_code", 137)))
        msg = rule.get(
            "message", f"injected {fire_kind} fault at {site!r} call {index}"
        )
        if fire_kind == "permanent":
            raise PermanentFault(msg, site=site, index=index)
        raise TransientFault(msg, site=site, index=index)

    # -- activation -----------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev
        self._prev = None


_ACTIVE: Optional[FaultInjector] = None
_ENV_CHECKED = False


def fault_plan(plan: Dict[str, Any], seed: int = 0) -> FaultInjector:
    """Build a :class:`FaultInjector`; use as a context manager to
    activate it for the enclosed block."""
    return FaultInjector(plan, seed=seed)


def _load_env_plan() -> Optional[FaultInjector]:
    raw = os.environ.get(PLAN_ENV)
    if not raw:
        return None
    raw = raw.strip()
    if not raw.startswith("{") and os.path.exists(raw):
        with open(raw) as f:
            raw = f.read()
    spec = json.loads(raw)
    if "plan" in spec and isinstance(spec["plan"], dict):
        return FaultInjector(spec["plan"], seed=int(spec.get("seed", 0)))
    return FaultInjector(spec)


def refresh_env_plan() -> Optional[FaultInjector]:
    """(Re-)read ``HEAT_TPU_FAULT_PLAN`` and activate it process-wide.

    Called lazily by the first :func:`inject`; call explicitly after
    changing the env var mid-process (tests)."""
    global _ACTIVE, _ENV_CHECKED
    _ENV_CHECKED = True
    inj = _load_env_plan()
    if inj is not None:
        _ACTIVE = inj
    return inj


def active_injector() -> Optional[FaultInjector]:
    """The currently active injector, or None."""
    return _ACTIVE


def inject(site: str, **info) -> None:
    """Evaluate the injection point ``site``.

    No-op (one global read) without an active plan; with one, records
    the hit and raises the scripted fault when the plan triggers."""
    global _ENV_CHECKED
    if _ACTIVE is None:
        if _ENV_CHECKED:
            return
        refresh_env_plan()
        if _ACTIVE is None:
            return
    _ACTIVE.check(site, info)


def fault_stats() -> Dict[str, int]:
    """Process-lifetime injection totals (bench counters) — a thin view
    over the shared telemetry registry (``fault.*``)."""
    return {
        "sites_evaluated": _SITES_EVALUATED.value,
        "faults_injected": _FAULTS_INJECTED.value,
    }


def reset_fault_stats() -> None:
    """Zero the injection totals; delegates to
    ``telemetry.reset_all("faults")``."""
    from ..telemetry import reset_all

    reset_all("faults")
