"""Parallel random number generation, analog of heat/core/random.py.

The reference implements two pRNGs (random.py:1-14): a torch-backed
"Batchparallel" mode (per-rank seed = seed + rank, weakly reproducible) and
a hand-written counter-based Threefry (:1016-1218) whose counter sequence
(:75-221) makes draws bit-identical for any process count.

JAX's native PRNG *is* counter-based Threefry, so the entire hand-rolled
machinery (32/64-bit block generation, mantissa masking :242-271, Kundu /
Box-Muller transforms :272-293) collapses: a single global
``jax.random.*`` draw with a derived key is deterministic in the global
seed and independent of the device count by construction — the stronger of
the reference's two guarantees, for free.
"""

from __future__ import annotations

import os

builtins_bytes = bytes
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.comm import sanitize_comm
from . import types
from .devices import sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "bytes",
    "choice",
    "default_seed",
    "get_state",
    "normal",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random",
    "random_integer",
    "random_sample",
    "randperm",
    "random_integers",
    "ranf",
    "shuffle",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

__seed: int = 0
__counter: int = 0


def default_seed() -> int:
    """A fresh 31-bit seed from OS entropy (``os.urandom``).

    The sanctioned source for "no seed given" seeding: the previous
    millisecond-clock fallback (``int(time.time() * 1000)``) collides
    across hosts launched in the same millisecond — exactly the pod
    bring-up case, where every worker would then draw identical
    "random" streams.  The AST linter's H601 rule points clock-based
    seeding here."""
    return int.from_bytes(os.urandom(4), "little") & 0x7FFFFFFF


def seed(new_seed: Optional[int] = None) -> None:
    """Seed the generator (random.py:885).  With no argument the seed
    comes from :func:`default_seed` (OS entropy — collision-free across
    hosts, unlike a millisecond clock); an explicit seed is used as
    given, so seeded runs stay bit-deterministic."""
    global __seed, __counter
    if new_seed is None:
        new_seed = default_seed()
    __seed = int(new_seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Current RNG state tuple (random.py:222), shaped like the reference's
    ('Threefry', seed, counter, _, _)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore an RNG state (random.py:914)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise ValueError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("this generator is based on Threefry")
    __seed = int(state[1])
    __counter = int(state[2])


def _next_key() -> jax.Array:
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter)
    __counter += 1
    return key


def _wrap(data, split, device, comm) -> DNDarray:
    comm = sanitize_comm(comm)
    device = sanitize_device(device)
    return DNDarray.from_dense(data, sanitize_axis(data.shape, split), device, comm)


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal distribution with given mean/std (random.py:293)."""
    if shape is None:
        shape = (1,)
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    std_arr = std._dense() if isinstance(std, DNDarray) else jnp.asarray(std)
    if bool(jnp.any(std_arr < 0)):
        raise ValueError("std needs to be positive")
    mean_arr = mean._dense() if isinstance(mean, DNDarray) else jnp.asarray(mean)
    data = jax.random.normal(_next_key(), shape, dtype=dtype.jax_type())
    data = data * std_arr + mean_arr
    return _wrap(data, split, device, comm)


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of a sequence / shuffled copy (random.py:666)."""
    key = _next_key()
    if isinstance(x, int):
        data = jax.random.permutation(key, x)
        data = data.astype(types.canonical_dtype(jnp.int64))
        return _wrap(data, split, device, comm)
    if isinstance(x, DNDarray):
        data = jax.random.permutation(key, x._dense(), axis=0)
        return _wrap(data, split if split is not None else x.split, device or x.device, comm or x.comm)
    data = jax.random.permutation(key, jnp.asarray(x), axis=0)
    return _wrap(data, split, device, comm)


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples of the given shape (random.py:308)."""
    if not d:
        d = (1,)
    shape = sanitize_shape(d)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.uniform(_next_key(), shape, dtype=dtype.jax_type())
    return _wrap(data, split, device, comm)


def randint(low, high=None, size=None, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Random integers in [low, high) (random.py:405)."""
    if high is None:
        low, high = 0, low
    if low >= high:
        raise ValueError("low >= high")
    if size is None:
        size = (1,)
    if isinstance(size, int):
        size = (size,)
    size = sanitize_shape(size)
    if dtype is None:
        dtype = types.int64 if jax.config.jax_enable_x64 else types.int32
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.int64, types.int32):
        raise ValueError(f"Unsupported dtype for randint, got {dtype}")
    data = jax.random.randint(_next_key(), size, int(low), int(high), dtype=dtype.jax_type())
    return _wrap(data, split, device, comm)


random_integer = randint


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples of the given shape (random.py:474)."""
    if not d:
        d = (1,)
    shape = sanitize_shape(d)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.normal(_next_key(), shape, dtype=dtype.jax_type())
    return _wrap(data, split, device, comm)


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (random.py:530)."""
    if shape is None:
        shape = (1,)
    return rand(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


random = random_sample
ranf = random_sample
sample = random_sample


def randperm(n: int, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of range(n) (random.py:625)."""
    if not isinstance(n, int):
        raise TypeError(f"n must be an integer, got {type(n)}")
    if dtype is None:
        dtype = types.int64 if jax.config.jax_enable_x64 else types.int32
    data = jax.random.permutation(_next_key(), n).astype(
        types.canonical_heat_type(dtype).jax_type()
    )
    return _wrap(data, split, device, comm)


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (random.py:693)."""
    if shape is None:
        shape = (1,)
    return randn(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


def uniform(low=0.0, high=1.0, size=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [low, high) samples (random.py:761)."""
    if size is None:
        size = (1,)
    size = sanitize_shape(size)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.uniform(
        _next_key(), size, dtype=dtype.jax_type(), minval=float(low), maxval=float(high)
    )
    return _wrap(data, split, device, comm)


def choice(a, size=None, replace: bool = True, p=None, split=None, device=None, comm=None) -> DNDarray:
    """Random sample from a 1-D array or range(a) (NumPy extension beyond
    the reference's random exports)."""
    from .dndarray import DNDarray

    if isinstance(a, DNDarray):
        pool = a._dense()
    elif isinstance(a, int):
        pool = jnp.arange(a)
    else:
        pool = jnp.asarray(a)
    shape = () if size is None else sanitize_shape(size)
    pd = None
    if p is not None:
        pd = p._dense() if isinstance(p, DNDarray) else jnp.asarray(p)
    data = jax.random.choice(_next_key(), pool, shape=shape, replace=replace, p=pd)
    # size=None returns a 0-d array (np.random.choice returns a scalar;
    # the 0-d DNDarray is the library's scalar form, item()-able)
    return _wrap(data, split, device, comm)


def shuffle(x) -> None:
    """Shuffle a DNDarray in place along its first axis (np.random.shuffle)."""
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        raise TypeError(f"x must be a DNDarray, got {type(x)}")
    perm = jax.random.permutation(_next_key(), x.shape[0])
    x._replace_local(jnp.take(x._dense(), perm, axis=0))


def bytes(length: int) -> builtins_bytes:
    """``length`` random bytes (np.random.bytes)."""
    bits = jax.random.randint(_next_key(), (int(length),), 0, 256, dtype=jnp.int32)
    return builtins_bytes(np.asarray(bits, dtype=np.uint8).tobytes())


def random_integers(low, high=None, size=None, split=None, device=None, comm=None) -> DNDarray:
    """Closed-interval integer samples (legacy np.random.random_integers)."""
    if high is None:
        low, high = 1, low
    return randint(low, int(high) + 1, size=size, split=split, device=device, comm=comm)
