"""Hierarchical (2-axis mesh) DASO tests.

Reference: heat/optim/dp_optimizer.py:64-850 (DASO: node-local DDP sync
every batch, cross-node bf16 parameter averaging every ``global_skips``
batches with delayed application) and heat/nn/data_parallel.py:313
(DataParallelMultiGPU).  The TPU-native topology is a
(n_node, per_node) mesh; these tests derive the grid from the CI
lane's mesh size (8 -> (2, 4), 3 -> (3, 1)).
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.parallel import HierarchicalCommunication


def _grid():
    """(n_node, per_node) that tiles whatever mesh the CI lane runs."""
    n = ht.get_comm().size
    return (2, n // 2) if n % 2 == 0 else (n, 1)


def test_hier_comm_topology():
    hc = HierarchicalCommunication(grid=_grid())
    assert (hc.num_nodes, hc.node_size) == _grid()
    assert hc.size == ht.get_comm().size
    assert hc.global_axis == "global"
    assert hc.node_axis == "node"
    assert hc.is_distributed
    assert f"nodes={_grid()[0]}" in repr(hc)


def test_hier_comm_bad_grid():
    with pytest.raises(ValueError):
        HierarchicalCommunication(grid=(ht.get_comm().size, 4))._ensure()


def test_hier_comm_as_data_comm():
    # drop-in Communication: a split array shards over the flattened grid
    hc = HierarchicalCommunication(grid=_grid())
    x = ht.arange(17, dtype=ht.float32, split=0, comm=hc)
    assert x.shape == (17,)
    np.testing.assert_array_equal(x.numpy(), np.arange(17, dtype=np.float32))
    s = ht.sum(x)
    assert float(s) == float(np.arange(17).sum())


def test_daso_replicate_collect():
    import jax.numpy as jnp
    import optax

    hc = HierarchicalCommunication(grid=_grid())
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(0.1), total_epochs=10, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    assert daso.hierarchical
    n = _grid()[0]
    params = {"w": jnp.ones((4,), jnp.float32), "b": jnp.zeros((2, 3), jnp.float32)}
    stacked = daso.replicate(params)
    assert stacked["w"].shape == (n, 4)
    assert stacked["b"].shape == (n, 2, 3)
    back = daso.collect(stacked)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.ones(4))


def test_daso_global_sync_is_a_real_average():
    """Replicas diverge while skipping and converge to the cross-node mean
    at the sync batch — the observable semantics of the reference's
    _global_sync (dp_optimizer.py:450)."""
    import jax.numpy as jnp
    import optax

    hc = HierarchicalCommunication(grid=_grid())
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(0.1), total_epochs=100, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    daso.global_skip = 4
    daso.batches_to_wait = 0

    n = _grid()[0]
    params = daso.replicate({"w": jnp.ones((4,), jnp.float32)})
    # node i sees gradient 1 + 2i every batch (distinct per node)
    gvals = np.array([1.0 + 2 * i for i in range(n)])
    grads = {"w": jnp.stack([jnp.full((4,), g, jnp.float32) for g in gvals])}
    gbar = gvals.mean()

    # batch 0: local step then sync (0 % 4 == 0) -> mean(1 - 0.1 * g_i)
    params = daso.step(params, grads)
    w = np.asarray(params["w"], dtype=np.float64)
    np.testing.assert_allclose(w[0], 1.0 - 0.1 * gbar, atol=1e-2)
    np.testing.assert_allclose(w[0], w[-1], atol=1e-7)

    # batches 1-3: no sync -> replicas diverge by per-node gradients
    for k in range(3):
        params = daso.step(params, grads)
        w = np.asarray(params["w"], dtype=np.float64)
        assert abs(w[0, 0] - w[-1, 0]) > 0.1 * (k + 1) * (gvals[-1] - gvals[0]) * 0.95, (k, w)

    # batch 4: sync -> replicas equal again, at the true cross-node mean
    params = daso.step(params, grads)
    w = np.asarray(params["w"], dtype=np.float64)
    np.testing.assert_allclose(w[0], w[-1], atol=1e-7)
    # trajectory mean: 1 - 5 * 0.1 * mean(g) = 1 - 0.5 * gbar
    np.testing.assert_allclose(w[0], 1.0 - 0.5 * gbar, atol=3e-2)


def test_daso_sync_lowers_to_cross_node_allreduce():
    """The compiled global sync must contain a cross-partition collective
    (the DCN psum), not just a cast."""
    import jax
    import jax.numpy as jnp
    import optax

    hc = HierarchicalCommunication(grid=_grid())
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(0.1), total_epochs=10, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    stacked = daso.replicate({"w": jnp.ones((64,), jnp.float32)})
    txt = daso._bf16_roundtrip.lower(stacked).compile().as_text()
    assert ("all-reduce" in txt) or ("all-gather" in txt) or ("collective" in txt), txt[:2000]


def test_daso_delayed_application():
    import jax.numpy as jnp
    import optax

    hc = HierarchicalCommunication(grid=_grid())
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(0.1), total_epochs=100, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    daso.global_skip = 2
    daso.batches_to_wait = 1
    n = _grid()[0]
    params = daso.replicate({"w": jnp.ones((4,), jnp.float32)})
    grads = {"w": jnp.stack([jnp.full((4,), 1.0 + 2 * i, jnp.float32) for i in range(n)])}

    # batch 0: sync computed but applied one batch later
    params = daso.step(params, grads)
    w = np.asarray(params["w"])
    assert abs(w[0, 0] - w[-1, 0]) > 0.1  # not yet applied
    assert daso._pending is not None
    # batch 1: the stale average lands (replacing local progress)
    params = daso.step(params, grads)
    w = np.asarray(params["w"])
    np.testing.assert_allclose(w[0], w[-1], atol=1e-7)
    # last_batch force-applies any in-flight average
    params = daso.step(params, grads)  # batch 2: sync scheduled again
    params = daso.last_batch(params)
    w = np.asarray(params["w"])
    np.testing.assert_allclose(w[0], w[-1], atol=1e-7)


def test_data_parallel_multi_gpu_trains(mlp_factory=None):
    import jax
    import optax

    rng = np.random.default_rng(0)
    X = rng.normal(size=(48, 8)).astype(np.float32)  # 48 divides 2- and 3-node grids
    w_true = rng.normal(size=(8,)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.int32)

    import flax.linen as lnn

    class MLP(lnn.Module):
        @lnn.compact
        def __call__(self, x):
            x = lnn.Dense(32)(x)
            x = lnn.relu(x)
            return lnn.Dense(2)(x)

    hc = HierarchicalCommunication(grid=_grid())
    daso = ht.optim.DASO(
        local_optimizer=optax.adam(1e-2), total_epochs=100, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    daso.global_skip = 4
    daso.batches_to_wait = 0
    dp = ht.nn.DataParallelMultiGPU(MLP(), daso=daso)
    dp.init(jax.random.PRNGKey(0), X)
    assert jax.tree_util.tree_leaves(dp.params)[0].shape[0] == _grid()[0]  # per-node replicas

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    losses = [dp.step(loss_fn, X, y) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    pred = np.argmax(np.asarray(dp(X)), axis=1)
    assert np.mean(pred == y) > 0.85

    final = daso.collect(daso.last_batch(dp.params))
    for f, s in zip(jax.tree_util.tree_leaves(final), jax.tree_util.tree_leaves(dp.params)):
        assert f.shape == s.shape[1:]  # node dim stripped


def test_daso_differs_from_plain_dp():
    """With skipped syncs and per-node data, DASO's trajectory measurably
    differs from every-batch averaging (plain DP) — the skip is real."""
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(1)
    X = rng.normal(size=(48, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=(48,)).astype(np.int32)

    import flax.linen as lnn

    class Tiny(lnn.Module):
        @lnn.compact
        def __call__(self, x):
            return lnn.Dense(2)(x)

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    def run(skip):
        hc = HierarchicalCommunication(grid=_grid())
        daso = ht.optim.DASO(
            local_optimizer=optax.adam(1e-2), total_epochs=100, comm=hc,
            warmup_epochs=0, cooldown_epochs=0,
        )
        daso.global_skip = skip
        daso.batches_to_wait = 0
        dp = ht.nn.DataParallelMultiGPU(Tiny(), daso=daso)
        dp.init(jax.random.PRNGKey(0), X)
        for _ in range(7):
            dp.step(loss_fn, X, y)
        return np.asarray(jax.tree_util.tree_leaves(daso.collect(dp.params))[0])

    w_sync_every = run(0)
    w_skipped = run(5)
    assert not np.allclose(w_sync_every, w_skipped, atol=1e-6)
