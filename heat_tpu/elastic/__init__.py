"""Elastic multi-host execution: survive worker loss, reshape the mesh,
resume the fit.

The reference framework's L1 layer assumes a fixed MPI world for the
life of the program (``heat/core/communication.py``); the TPU reality
this framework targets is **preemptible pods** — workers vanish mid-fit
and capacity comes back at a different size.  This package composes the
pieces the earlier layers built into a recovery loop:

detect -> reshape -> resume
---------------------------
* **detect** — worker loss surfaces either as a typed exception
  (:class:`~heat_tpu.resilience.errors.WorkerLostError`, a failed
  collective) in-process, or as a dead/stale worker process under the
  :class:`~heat_tpu.elastic.process.ProcessSupervisor` (exit code +
  the ``fit.heartbeat_ts``-backed heartbeat file every
  ``resumable_fit_loop`` chunk boundary touches when
  ``HEAT_TPU_HEARTBEAT_FILE`` is set).  Fault site ``elastic.detect``.
* **reshape** — ``comm.reshape(n)`` rebuilds the (ICI-node x
  DCN-global) mesh metadata for the surviving device set
  (:meth:`~heat_tpu.parallel.comm.Communication.reshape`); all
  distribution metadata (``chunk``/``lshape_map``/``sharding``) is a
  pure function of (shape, split, size) and recomputes implicitly.
  Live arrays move with :meth:`~heat_tpu.core.dndarray.DNDarray.reshard_`;
  checkpointed state re-splits through
  ``Checkpointer.restore(..., comm=new)``.  Bounded-retry under the
  init :class:`~heat_tpu.resilience.retry.RetryPolicy`; fault site
  ``elastic.reshape``.
* **resume** — the fit re-enters ``resumable_fit_loop`` with
  ``resume_from=<checkpoint_dir>``: the iteration sequence continues
  from the last durable step on the new world.  Same-size resume stays
  bitwise identical (the PR 2/3 property); a smaller world converges to
  the same result within floating-point reduction-order tolerance.
  Fault site ``elastic.resume``.

Telemetry: ``elastic.worker_losses`` / ``elastic.reshapes`` counters,
``elastic.recovery_ms`` histogram, ``elastic.world_size`` gauge — all in
the process-global registry, so they flow into ``/metrics``, ``/varz``,
crash flight-recorder bundles, and the ``/statusz`` elastic section.

See ``docs/elasticity.md`` for the walkthrough and the failure-mode
table.
"""

from __future__ import annotations

from ..resilience.errors import ReshapeError, WorkerLostError
from .supervisor import ElasticSupervisor, HeartbeatMonitor, elastic_state
from .process import ProcessSupervisor, kmeans_worker_source

__all__ = [
    "ElasticSupervisor",
    "HeartbeatMonitor",
    "ProcessSupervisor",
    "ReshapeError",
    "WorkerLostError",
    "elastic_state",
    "kmeans_worker_source",
]
