"""FFT continuous benchmarks: pencil-decomposed split-axis transforms.

The reference has no FFT cb suite; this one tracks the round-2 pencil
collective (all_to_all transpose instead of GSPMD's all-gather) on the
shapes the 3-D FFT baseline config uses."""

# flake8: noqa
import heat_tpu as ht
from monitor import monitor


@monitor()
def fft_split_axis(volume):
    return ht.fft.fft(volume, axis=0)


@monitor()
def fftn_pencil(volume):
    return ht.fft.fftn(volume)


@monitor()
def fft_roundtrip(volume):
    return ht.fft.ifftn(ht.fft.fftn(volume))


def run_fft_benchmarks(scale: float = 1.0):
    s = max(int(128 * scale), 16)
    p = ht.get_comm().size
    s = -(-s // p) * p  # divisible partner extents for the pencil path
    vol = ht.random.randn(s, s, s, split=0).astype(ht.float32)
    fft_split_axis(vol)
    fftn_pencil(vol)
    fft_roundtrip(vol)
