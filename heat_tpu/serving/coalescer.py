"""Request coalescer: many concurrent ``predict()`` calls, one dispatch.

The serving hot path must never pay per-request what the framework
amortizes per batch — Python dispatch, DNDarray wrapping, an XLA
launch.  Each served model gets one :class:`ModelBatcher`: callers
enqueue their rows and block on a per-request event; a dedicated
batcher thread drains the queue into one batch per **tick** (up to
``HEAT_TPU_SERVE_MAX_BATCH`` rows, waiting at most
``HEAT_TPU_SERVE_MAX_DELAY_MS`` from the first queued request), pads
the batch up to a **bucket** shape
(:func:`heat_tpu.core.dispatch.batch_bucket`: next power of two), runs
ONE estimator inference over the padded batch, and scatters each
caller's slice of the result back.

The bucket padding is what keeps the executable-cache key set finite:
request traffic produces arbitrary batch sizes, but the dispatch layer
only ever sees ``log2(max_batch)+1`` distinct leading extents — after
one warmup pass per bucket, steady-state serving triggers **zero new
compiles** whatever the traffic mix (the ``bench_serving`` acceptance
gate).  Pad rows are real zero rows (not mask metadata), so the true
extent baked into cached programs is the bucket itself; pad outputs are
simply dropped by the scatter.

Lock discipline (sanitized by the TSAN lane): the queue is only touched
under the registered ``serving.coalescer`` lock via its Condition; the
inference itself — the blocking part — always runs *outside* the lock,
so enqueues never stall behind XLA.

**Request tracing** (:mod:`heat_tpu.telemetry.tracing`): ``submit()``
captures the caller's trace context into the request; the batch's
``serve.batch``/``serve.pad``/``serve.scatter`` (plus the service's
dispatch/execute) spans run under the *primary* (first traced) request's
context across the thread hop.  Per-request bookkeeping — the
``serve.coalesce_wait`` span for the time in queue, and mirroring the
batch records into co-batched traces — happens on each *woken caller*,
never on the batcher thread: the batcher is the throughput bottleneck
and pays only per-batch tracing work, while callers do their own
accounting in time they would have spent blocked anyway.  One slow
``/v1/predict`` therefore shows its whole pipeline in ``/tracez``
whichever batch slot it rode in.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..analysis import tsan as _tsan
from ..core import dispatch as _dispatch
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing
from ..telemetry.spans import clear_notes as _clear_notes
from ..telemetry.spans import flush_notes as _flush_notes
from ..telemetry.spans import stage_note as _stage_note

__all__ = ["ModelBatcher", "observe_stage"]

_BATCHES_C = _tm.counter("serving.batches", "coalesced inference dispatches")
_BATCH_ROWS_H = _tm.histogram(
    "serving.batch_rows", "true rows per coalesced inference batch"
)
_PAD_ROWS_C = _tm.counter(
    "serving.pad_rows", "bucket-padding rows dispatched (wasted compute rows)"
)

#: per-stage latency decomposition of one served request — the
#: histograms that replace eyeballing a single end-to-end number.
#: Exemplars (most recent trace_id per bucket) connect each bucket to a
#: retained trace in /tracez.
_STAGES = ("admission", "coalesce", "pad", "dispatch", "execute", "scatter")
_STAGE_H = {
    s: _tm.histogram(
        f"serving.stage.{s}_ms",
        f"per-request serving latency decomposition: the {s} stage",
    )
    for s in _STAGES
}


def observe_stage(stage: str, ms: float, trace_id: Optional[str] = None) -> None:
    """Observe one serving-stage duration, exemplared with the given (or
    the ambient) trace id when exemplars are enabled."""
    if trace_id is None:
        trace_id = _tracing.current_trace_id()
    # direct module-flag read: this runs up to 6x per request
    _STAGE_H[stage].observe(
        ms, exemplar=trace_id if (trace_id and _tracing._EXEMPLARS) else None
    )


class _Request:
    __slots__ = ("rows", "n", "event", "result", "error", "enqueued_at",
                 "enqueued_ns", "ctx", "taken_ns", "primary_trace_id",
                 "batch_records")

    def __init__(self, rows: np.ndarray):
        self.rows = rows
        self.n = int(rows.shape[0])
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.enqueued_ns = time.perf_counter_ns()  # span clock for coalesce_wait
        self.ctx = _tracing.current_context()  # caller -> batcher handoff
        # stamped by the batcher, consumed by the caller after wake-up:
        # the caller records its own coalesce_wait span and mirrors the
        # batch's raw note batch into its trace, so the batcher thread —
        # the throughput bottleneck — pays no per-request tracing work
        self.taken_ns: Optional[int] = None
        self.primary_trace_id: Optional[str] = None
        self.batch_records: Optional[tuple] = None


class ModelBatcher:
    """One model's coalescing queue + batcher thread.

    ``infer_fn(batch_rows: np.ndarray) -> np.ndarray`` is the model
    inference over a padded batch (the service wires it to the
    registry's *active* version at every tick, so a promote/rollback
    applies from the next batch with zero downtime).
    """

    def __init__(
        self,
        name: str,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int,
        max_delay_s: float,
        on_batch: Optional[Callable[[np.ndarray], None]] = None,
        on_mirror: Optional[Callable[..., Any]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.name = name
        self._infer_fn = infer_fn
        #: post-batch hook: called with the TRUE (un-padded) rows after
        #: every waiting caller has been woken — work here (the input
        #: drift sketches) is off every caller's latency path by
        #: construction, the data analogue of the deferred stage notes
        self._on_batch = on_batch
        #: shadow-mirror hook: called with ``(true_rows, true_outputs,
        #: primary_trace_id, infer_ms)`` after the callers are woken —
        #: the canary decision plane's tap into the scatter path, same
        #: off-the-latency-path placement as ``on_batch``
        self._on_mirror = on_mirror
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        self._queue: List[_Request] = []
        self._queued_rows = 0
        self._open = True
        self.last_batch_ts = 0.0
        self.last_batch_trace_id: Optional[str] = None
        self._lock = _tsan.register_lock("serving.coalescer")
        self._cond = threading.Condition(self._lock)
        self._thread = threading.Thread(
            target=self._run, name=f"heat-tpu-serve-{name}", daemon=True
        )
        self._thread.start()

    # -- caller side ----------------------------------------------------
    def submit(self, rows: np.ndarray, timeout: Optional[float] = None) -> np.ndarray:
        """Enqueue ``rows`` and block until their predictions return.

        Raises the batch's inference error if its dispatch failed,
        ``TimeoutError`` past ``timeout``, ``RuntimeError`` after
        ``close()``."""
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError(f"rows must be 2-D (n, features), got shape {rows.shape}")
        if rows.shape[0] == 0:
            return rows[:0]
        if rows.shape[0] > self.max_batch:
            raise ValueError(
                f"request of {rows.shape[0]} rows exceeds the coalescer's "
                f"max batch {self.max_batch} (HEAT_TPU_SERVE_MAX_BATCH); "
                "split the request"
            )
        req = _Request(rows)
        with self._cond:
            _tsan.note_access("serving.coalescer.queue")
            if not self._open:
                raise RuntimeError(f"batcher for model {self.name!r} is closed")
            self._queue.append(req)
            self._queued_rows += req.n
            self._cond.notify_all()
        if not req.event.wait(timeout):
            # the batcher may still complete it; the caller stops waiting
            raise TimeoutError(
                f"predict on model {self.name!r} timed out after {timeout}s"
            )
        if req.ctx is not None and req.taken_ns is not None:
            # trace bookkeeping runs HERE, on the woken caller (its trace
            # context is still ambient), never on the batcher thread: the
            # caller notes its queue wait (materialized when its request
            # root flushes) and — when it rode another request's batch —
            # mirrors the shared batch records into its own trace
            wait_ns = req.taken_ns - req.enqueued_ns
            _stage_note(
                "serve.coalesce_wait", req.enqueued_ns, wait_ns,
                model=self.name, rows=req.n,
            )
            observe_stage("coalesce", wait_ns / 1e6, req.ctx.trace_id)
            if req.batch_records is not None and req.ctx.trace_id != req.primary_trace_id:
                _tracing.link_batch([req.ctx.trace_id], req.batch_records)
        if req.error is not None:
            raise req.error
        return req.result

    def queued_rows(self) -> int:
        with self._lock:
            _tsan.note_access("serving.coalescer.queue", write=False)
            return self._queued_rows

    def alive(self) -> bool:
        """Whether the batcher thread is serving (per-model /healthz)."""
        return self._thread.is_alive() and self._open

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, drain queued requests, join the batcher
        thread.  Idempotent and safe to call concurrently."""
        with self._cond:
            _tsan.note_access("serving.coalescer.queue")
            self._open = False
            self._cond.notify_all()
        t = self._thread
        if t is not threading.current_thread():
            t.join(timeout)

    # -- batcher thread -------------------------------------------------
    def _take_batch(self) -> List[_Request]:
        """Pop requests up to max_batch rows (caller holds the lock)."""
        batch: List[_Request] = []
        rows = 0
        while self._queue and rows + self._queue[0].n <= self.max_batch:
            req = self._queue.pop(0)
            rows += req.n
            batch.append(req)
        self._queued_rows -= rows
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                _tsan.note_access("serving.coalescer.queue")
                while self._open and not self._queue:
                    self._cond.wait()
                if not self._open and not self._queue:
                    return
                # batching window: from the first queued request, wait
                # for more work until the delay elapses or a full batch
                # is ready — the latency/throughput dial of the design
                deadline = self._queue[0].enqueued_at + self.max_delay_s
                while self._open and self._queued_rows < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch = self._take_batch()
            if batch:
                self._execute(batch)  # outside the lock: XLA must not block enqueues

    def _execute(self, batch: List[_Request]) -> None:
        taken_ns = time.perf_counter_ns()
        for r in batch:
            r.taken_ns = taken_ns  # callers derive their queue wait
        try:
            _inject("serve.batch", model=self.name)
            n = sum(r.n for r in batch)
            bucket = _dispatch.batch_bucket(n, self.max_batch)
            n_traced = sum(1 for r in batch if r.ctx is not None)
            primary = next((r.ctx for r in batch if r.ctx is not None), None)
            ptid = primary.trace_id if primary is not None else None
            # batch-level stages (pad/dispatch/execute/scatter and the
            # batch envelope) are NOTED under the primary request's
            # context and materialized in one flush; the woken callers
            # mirror the records into their co-batched traces (see
            # submit()), so each retained trace is complete while the
            # batcher thread pays only one buffered append per stage
            with _tracing.use_context(primary):
                tb0 = time.perf_counter_ns()
                rows = np.concatenate([r.rows for r in batch], axis=0)
                if bucket > n:
                    pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
                    rows = np.concatenate([rows, pad], axis=0)
                t1 = time.perf_counter_ns()
                _stage_note("serve.pad", tb0, t1 - tb0, rows=n, bucket=bucket)
                observe_stage("pad", (t1 - tb0) / 1e6, ptid)
                ti0 = time.perf_counter_ns()
                out = np.asarray(self._infer_fn(rows))
                infer_ms = (time.perf_counter_ns() - ti0) / 1e6
                t0 = time.perf_counter_ns()
                off = 0
                for r in batch:
                    r.result = out[off : off + r.n]
                    off += r.n
                t1 = time.perf_counter_ns()
                _stage_note("serve.scatter", t0, t1 - t0, requests=len(batch))
                observe_stage("scatter", (t1 - t0) / 1e6, ptid)
                _stage_note(
                    "serve.batch", tb0, t1 - tb0,
                    model=self.name, rows=n, bucket=bucket, traces=n_traced,
                )
                records = _flush_notes()
            _BATCHES_C.inc()
            _BATCH_ROWS_H.observe(n)
            _PAD_ROWS_C.inc(bucket - n)
            self.last_batch_ts = time.time()
            self.last_batch_trace_id = ptid
            # wake the callers only after every shared field is in place
            for r in batch:
                r.primary_trace_id = ptid
                r.batch_records = records
                r.event.set()
            if self._on_batch is not None:
                # callers are already awake: the hook's cost lands on
                # the batcher thread between ticks, never on a request
                try:
                    self._on_batch(rows[:n])
                except Exception:  # lint: allow H501(a sketch bug must never fail served requests)
                    pass
            if self._on_mirror is not None:
                # shadow mirroring: the hook only samples + enqueues (a
                # bounded queue another thread drains) — same contract
                try:
                    self._on_mirror(rows[:n], out[:n], ptid, infer_ms)
                except Exception:  # lint: allow H501(a canary bug must never fail served requests)
                    pass
        except BaseException as e:  # lint: allow H501(per-request error delivery; the batcher thread must survive)
            _clear_notes()  # a failed batch must not leak notes into the next
            for r in batch:
                if not r.event.is_set():
                    r.error = e
                    r.event.set()
