"""Timing monitor for the continuous-benchmark suite.

The reference instruments its cb functions with the external ``perun``
energy/runtime monitor (benchmarks/cb/linalg.py:4, setup.py extras
``cb=perun``).  perun is MPI-bound; the TPU-native stand-in measures
wall time around a fully-synchronized call (``jax.block_until_ready`` on
every jax array in the result) and emits one JSON line per benchmark —
the same shape the round driver's bench.py reports.
"""

from __future__ import annotations

import functools
import json
import time
from typing import Any

import jax

RESULTS = []


def _sync(obj: Any) -> None:
    if hasattr(obj, "larray_padded"):
        jax.block_until_ready(obj.larray_padded)
    elif isinstance(obj, jax.Array):
        jax.block_until_ready(obj)
    elif isinstance(obj, (tuple, list)):
        for o in obj:
            _sync(o)
    elif isinstance(obj, dict):
        for o in obj.values():
            _sync(o)


def monitor():
    """Decorator mirroring perun's ``@monitor()`` (benchmarks/cb usage)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            _sync(out)
            elapsed = time.perf_counter() - t0
            record = {"bench": fn.__name__, "seconds": round(elapsed, 6)}
            RESULTS.append(record)
            print(json.dumps(record), flush=True)
            return out

        return wrapper

    return deco
