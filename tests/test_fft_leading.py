"""Leading-contraction 3-D FFT engine (fft/_leading.py).

Numpy is ground truth throughout; the engine's default HIGH matmul
policy bounds f32 relative error around ~3e-5 at test sizes, so the
tolerances here are a few 1e-4.  Reference semantics:
heat/fft/fft.py:100-137 (fftn/ifftn).
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.fft import _leading, _planar, _weight_cache


def _rel(a, b):
    d = np.abs(a - b)
    return d.max() / max(np.abs(b).max(), 1e-12)


SHAPES = [(16, 16, 16), (8, 16, 32), (32, 8, 16), (6, 10, 12), (4, 4, 4)]


@pytest.mark.parametrize("shape", SHAPES)
def test_rfft3_leading_matches_numpy(shape):
    rng = np.random.default_rng(7)
    x = rng.standard_normal(shape).astype(np.float32)
    re, im = _leading.rfft3_leading(np.asarray(x), None)
    ref = np.fft.fftn(x.astype(np.float64))
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


@pytest.mark.parametrize("norm", [None, "ortho", "forward", "backward"])
def test_rfft3_leading_norms(norm):
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 8, 8)).astype(np.float32)
    re, im = _leading.rfft3_leading(np.asarray(x), norm)
    ref = np.fft.fftn(x.astype(np.float64), norm=norm)
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("shape", [(16, 16, 16), (8, 16, 32), (6, 10, 12)])
def test_cfft3_leading_matches_numpy(shape, inverse):
    rng = np.random.default_rng(11)
    xr = rng.standard_normal(shape).astype(np.float32)
    xi = rng.standard_normal(shape).astype(np.float32)
    re, im = _leading.cfft3_leading(np.asarray(xr), np.asarray(xi), inverse, None)
    z = xr.astype(np.float64) + 1j * xi.astype(np.float64)
    ref = np.fft.ifftn(z) if inverse else np.fft.fftn(z)
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


def test_leading_matches_interleaved_engine():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((16, 16, 16)).astype(np.float32)
    rl, il = _leading.rfft3_leading(np.asarray(x), None)
    ri, ii = _planar._rfft3_interleaved(np.asarray(x), None)
    assert _rel(np.asarray(rl), np.asarray(ri)) < 5e-4
    assert _rel(np.asarray(il) + 0.0, np.asarray(ii) + 0.0) < 5e-4


def test_eligibility_gates():
    import jax.numpy as jnp
    import jax

    re3 = jax.numpy.zeros((8, 8, 8), jnp.float32)
    assert _leading.leading_eligible(re3, [0, 1, 2], False)
    # odd leading axis only blocks the REAL (halved) path
    re_odd = jax.numpy.zeros((7, 8, 8), jnp.float32)
    assert not _leading.leading_eligible(re_odd, [0, 1, 2], False)
    assert _leading.leading_eligible(re_odd, [0, 1, 2], True)
    # 2-D and f64 ARE eligible since the round-3 generalization
    assert _leading.leading_eligible(jnp.zeros((8, 8), jnp.float32), [0, 1], False)
    assert _leading.leading_eligible(
        jnp.zeros((8, 8, 8), jnp.float64), [0, 1, 2], False
    )
    # wrong rank / dtype / partial axes
    assert not _leading.leading_eligible(jnp.zeros((8,), jnp.float32), [0], True)
    assert not _leading.leading_eligible(
        jnp.zeros((8, 8, 8), jnp.int32), [0, 1, 2], True
    )
    assert not _leading.leading_eligible(re3, [0, 1], False)


def test_fftn_user_path_rides_leading(monkeypatch):
    """ht.fft.fftn on an eligible cube goes through the leading engine
    (the engine's odd-shape fallback keeps parity for the rest)."""
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 12, 16)).astype(np.float32)
    out = ht.fft.fftn(ht.array(x))
    ref = np.fft.fftn(x.astype(np.float64))
    assert _rel(out.numpy(), ref) < 5e-4
    # complex input path
    z = x + 1j * rng.standard_normal((8, 12, 16)).astype(np.float32)
    out_c = ht.fft.fftn(ht.array(z.astype(np.complex64)))
    assert _rel(out_c.numpy(), np.fft.fftn(z.astype(np.complex128))) < 5e-4
    out_i = ht.fft.ifftn(ht.array(z.astype(np.complex64)))
    assert _rel(out_i.numpy(), np.fft.ifftn(z.astype(np.complex128))) < 5e-4


def test_leading_disabled_env(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FFT_LEADING", "0")
    import jax.numpy as jnp

    assert not _leading.leading_eligible(
        jnp.zeros((8, 8, 8), jnp.float32), [0, 1, 2], False
    )


def test_ext_fused_interpret_matches_xla(monkeypatch):
    """The combine-folding variant agrees with combine-then-extend."""
    rng = np.random.default_rng(10)
    m, n1, n2 = 8, 8, 128
    zr = rng.standard_normal((m, n1, 2 * n2)).astype(np.float32)
    zi = rng.standard_normal((m, n1, 2 * n2)).astype(np.float32)
    nyr = rng.standard_normal((n1, n2)).astype(np.float32)
    nyi = rng.standard_normal((n1, n2)).astype(np.float32)
    got = _leading._ext_fused_pallas(zr, zi, nyr, nyi)
    ere = zr[..., :n2] - zi[..., n2:]
    eim = zr[..., n2:] + zi[..., :n2]
    ref = _leading._ext_xla(ere, eim, nyr, nyi)
    assert _rel(np.asarray(got[0]), np.asarray(ref[0])) < 2e-4
    assert _rel(np.asarray(got[1]), np.asarray(ref[1])) < 2e-4


def test_stage_fused_interpret_matches_xla(monkeypatch):
    """The fused stage kernel (bf16x3 in-kernel dots + combine) agrees
    with the XLA cat-dot stage to the HIGH policy's error class."""
    rng = np.random.default_rng(4)
    n = 128  # transformed axis (= contracted dim)
    re = rng.standard_normal((n, 4, 64)).astype(np.float32)
    im = rng.standard_normal((n, 4, 64)).astype(np.float32)
    got = _leading._stage_fused_pallas(np.asarray(re), np.asarray(im), n, False, 1.0)
    import jax

    wcat = _leading._w_cat(n, "float32", False, 1.0)
    ref = _leading._stage(
        np.asarray(re), np.asarray(im), wcat, n, jax.lax.Precision.HIGHEST
    )
    assert _rel(np.asarray(got[0]), np.asarray(ref[0])) < 2e-4
    assert _rel(np.asarray(got[1]), np.asarray(ref[1])) < 2e-4


def test_rfft3_leading_all_kernels_forced(monkeypatch):
    """Force every Pallas path (cat entry + blocked mid kernel + fused
    extension) through the full real transform in interpret mode and pin
    against numpy.  m = n0//2 = 128 tiles, so the blocked branch engages."""
    monkeypatch.setattr(_leading, "_use_pallas_ext", lambda n1, n2: True)
    monkeypatch.setattr(_leading, "_use_fused_stage", lambda k, m, n: True)
    rng = np.random.default_rng(13)
    x = rng.standard_normal((256, 8, 128)).astype(np.float32)
    re, im = _leading.rfft3_leading(np.asarray(x), None)
    ref = np.fft.fftn(x.astype(np.float64))
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


def test_stage_blocked_interpret_matches_plain(monkeypatch):
    """The blocked-operand kernel (index-mapped re/im halves of a cat
    tensor) matches the separate-planes kernel."""
    rng = np.random.default_rng(14)
    k, b, m, n = 128, 4, 128, 128
    z = rng.standard_normal((k, b, 2 * m)).astype(np.float32)
    got = _leading._stage_fused_pallas_blocked(np.asarray(z), n, m, False, 1.0)
    re = z[..., :m]
    im = z[..., m:]
    ref = _leading._stage_fused_pallas(np.asarray(re), np.asarray(im), n, False, 1.0)
    assert np.allclose(np.asarray(got[0]), np.asarray(ref[0]), atol=1e-5)
    assert np.allclose(np.asarray(got[1]), np.asarray(ref[1]), atol=1e-5)


def test_rfft3_leading_fused_ext_path(monkeypatch):
    """Force the fused-extension branch (interpret mode off-TPU) on an
    aligned shape and pin it against numpy."""
    monkeypatch.setattr(_leading, "_use_pallas_ext", lambda n1, n2: True)
    rng = np.random.default_rng(12)
    x = rng.standard_normal((16, 8, 128)).astype(np.float32)
    re, im = _leading.rfft3_leading(np.asarray(x), None)
    ref = np.fft.fftn(x.astype(np.float64))
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


# ----------------------------------------------------------------------
# byte-bounded weight cache (ISSUE 2 satellite): the DFT weight builders
# share one LRU bounded by BYTES, not entry count, so sweeping sizes
# cannot pin ~1 GB of host RAM for the process lifetime.
# ----------------------------------------------------------------------
def test_weight_cache_stays_under_byte_budget(monkeypatch):
    monkeypatch.setattr(_weight_cache, "_WEIGHT_CACHE_BUDGET", 4 << 20)  # 4 MB
    _leading.weight_cache_clear()
    try:
        for n in (64, 96, 128, 192, 256, 320, 384):
            _leading._w_cat(n, "float32", False, 1.0)
            _leading._w_cat_bf(n, False, 1.0)
            _leading._w_entry_cat(n, n // 2, "float32")
        s = _leading.weight_cache_stats()
        assert s["nbytes"] <= s["budget_nbytes"] or s["entries"] == 1
        assert s["entries"] < 21  # some of the 21 inserts were evicted
    finally:
        _leading.weight_cache_clear()


def test_weight_cache_hit_returns_same_object_and_recomputes_after_eviction():
    _leading.weight_cache_clear()
    try:
        a = _leading._w_cat(32, "float32", False, 1.0)
        assert _leading._w_cat(32, "float32", False, 1.0) is a  # LRU hit
        _leading.weight_cache_clear()
        b = _leading._w_cat(32, "float32", False, 1.0)  # cold: recomputed
        assert b is not a
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    finally:
        _leading.weight_cache_clear()


def test_weight_cache_values_unchanged_by_eviction(monkeypatch):
    """Evicted-and-recomputed weights are bitwise identical — the cache
    is a pure memoization layer, never a source of drift."""
    monkeypatch.setattr(_weight_cache, "_WEIGHT_CACHE_BUDGET", 1 << 20)  # tiny: thrash
    _leading.weight_cache_clear()
    try:
        first = {n: np.asarray(_leading._w_cat(n, "float32", False, 1.0)).copy()
                 for n in (64, 128, 192)}
        for n in (256, 320, 384):  # push the earlier entries out
            _leading._w_cat(n, "float32", False, 1.0)
        for n, want in first.items():
            np.testing.assert_array_equal(
                np.asarray(_leading._w_cat(n, "float32", False, 1.0)), want
            )
    finally:
        _leading.weight_cache_clear()

# ----------------------------------------------------------------------
# Round-3 generalization: 2-D, f64, pair-block complex stages
# ----------------------------------------------------------------------
@pytest.mark.parametrize("shape", [(8, 12), (16, 16), (2, 5), (32, 8)])
@pytest.mark.parametrize("norm", [None, "ortho", "forward"])
def test_rfft2_leading_matches_numpy(shape, norm):
    rng = np.random.default_rng(13)
    x = rng.standard_normal(shape).astype(np.float32)
    re, im = _leading.rfft2_leading(np.asarray(x), norm)
    ref = np.fft.fftn(x.astype(np.float64), norm=norm)
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("shape", [(8, 12), (6, 10, 14)])
def test_cfftn_leading_matches_numpy(shape, inverse):
    rng = np.random.default_rng(17)
    xr = rng.standard_normal(shape).astype(np.float32)
    xi = rng.standard_normal(shape).astype(np.float32)
    re, im = _leading.cfftn_leading(np.asarray(xr), np.asarray(xi), inverse, None)
    z = xr.astype(np.float64) + 1j * xi.astype(np.float64)
    ref = np.fft.ifftn(z) if inverse else np.fft.fftn(z)
    got = np.asarray(re) + 1j * np.asarray(im)
    assert _rel(got, ref) < 5e-4


@pytest.mark.parametrize("shape", [(8, 12), (6, 10, 14)])
def test_leading_f64(shape):
    """f64 runs the leading engine (native dots off-TPU) to ~1e-11."""
    rng = np.random.default_rng(19)
    xr = rng.standard_normal(shape)
    xi = rng.standard_normal(shape)
    re, im = _leading.cfftn_leading(np.asarray(xr), np.asarray(xi), False, None)
    ref = np.fft.fftn(xr + 1j * xi)
    assert _rel(np.asarray(re) + 1j * np.asarray(im), ref) < 1e-10
    xe = rng.standard_normal((shape[0] - shape[0] % 2, shape[-1]))
    re, im = _leading.rfft2_leading(np.asarray(xe), None)
    assert _rel(np.asarray(re) + 1j * np.asarray(im), np.fft.fftn(xe)) < 1e-10


def test_pair_stage_fused_matches_xla():
    """The cat-output fused pair kernel (interpret mode off-TPU) agrees
    with the XLA pair-block dot within the bf16x3 error class."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(23)
    z = jnp.asarray(rng.standard_normal((128, 2, 2, 256)).astype(np.float32))
    ref = _leading._stage_pair(z, 128, False, 1.0, jax.lax.Precision.HIGHEST)
    got = _leading._stage_pair_fused(z, 128, False, 1.0)
    assert _rel(np.asarray(got), np.asarray(ref)) < 1e-4
    re = jnp.asarray(rng.standard_normal((128, 8, 32)).astype(np.float32))
    im = jnp.asarray(rng.standard_normal((128, 8, 32)).astype(np.float32))
    ze = _leading._entry_pair_fused(re, im, 128, False)
    zx = _leading._dg0(re, _leading._w_cat(128, "float32", False, 1.0),
                       jax.lax.Precision.HIGHEST) + \
        _leading._dg0(im, _leading._w_cat_im(128, "float32", False, 1.0),
                      jax.lax.Precision.HIGHEST)
    assert _rel(np.asarray(ze), np.asarray(zx).reshape(8, 32, 2, 128)) < 1e-4


def test_fft2_user_path_rides_leading():
    """ht.fft 2-D and f64 inputs take the leading engine (no fallback)."""
    rng = np.random.default_rng(29)
    x = rng.standard_normal((8, 12)).astype(np.float32)
    assert _rel(ht.fft.fft2(ht.array(x)).numpy(),
                np.fft.fft2(x.astype(np.float64))) < 5e-4
    z64 = rng.standard_normal((6, 10, 14)) + 1j * rng.standard_normal((6, 10, 14))
    assert _rel(ht.fft.fftn(ht.array(z64)).numpy(), np.fft.fftn(z64)) < 1e-10
