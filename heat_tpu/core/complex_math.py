"""Complex-number operations, analog of heat/core/complex_math.py."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x, deg: bool = False, out=None):
    """Argument of complex values (complex_math.py:15)."""
    return _local_op(lambda a: jnp.angle(a, deg=deg), x, out, no_cast=True)


def conjugate(x, out=None):
    """Complex conjugate (complex_math.py:48)."""
    return _local_op(jnp.conjugate, x, out, no_cast=True)


conj = conjugate


def imag(x, out=None):
    """Imaginary part (complex_math.py:78)."""
    return _local_op(jnp.imag, x, out, no_cast=True)


def real(x, out=None):
    """Real part (complex_math.py:98)."""
    return _local_op(jnp.real, x, out, no_cast=True)
