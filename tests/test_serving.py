"""Online serving layer: model registry, request coalescing, admission
control, and the /v1 HTTP surface.

The acceptance properties (ISSUE 9): save -> hot-load -> predict is
bitwise-identical per estimator (including a cross-world P != Q
restore), steady-state traffic triggers zero new compiles across varied
batch sizes (pad-to-bucket), over-quota tenants shed with a typed 429
while admitted traffic keeps its latency, and promote/rollback swap
versions with zero downtime.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import dispatch
from heat_tpu.resilience import OverloadedError, ReshapeError, faults
from heat_tpu.serving import model_io
from heat_tpu.serving.admission import AdmissionController, TokenBucket
from heat_tpu.serving.coalescer import ModelBatcher
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver
from heat_tpu.utils.checkpoint import Checkpointer

RNG = np.random.default_rng(0)
PTS = RNG.standard_normal((120, 6)).astype(np.float32)
LABELS = RNG.integers(0, 3, 120).astype(np.int64)
YREG = (PTS @ RNG.standard_normal(6) + 0.5).astype(np.float32)

ALL_KINDS = list(model_io.SUPPORTED_KINDS)


def _fit(kind):
    x = ht.array(PTS, split=0)
    if kind == "KMeans":
        return ht.cluster.KMeans(n_clusters=3, init="random", max_iter=5, random_state=0).fit(x)
    if kind == "KMedians":
        return ht.cluster.KMedians(n_clusters=3, init="random", max_iter=5, random_state=0).fit(x)
    if kind == "KMedoids":
        return ht.cluster.KMedoids(n_clusters=3, init="random", max_iter=5, random_state=0).fit(x)
    if kind == "PCA":
        return ht.decomposition.PCA(n_components=3).fit(x)
    if kind == "Lasso":
        return ht.regression.Lasso(lam=0.05, max_iter=20).fit(x, ht.array(YREG.reshape(-1, 1), split=0))
    if kind == "KNeighborsClassifier":
        return ht.classification.KNeighborsClassifier(n_neighbors=3).fit(x, ht.array(LABELS, split=0))
    raise AssertionError(kind)


@pytest.fixture
def fitted_kmeans():
    return _fit("KMeans")


@pytest.fixture
def kmeans_dir(tmp_path, fitted_kmeans):
    d = str(tmp_path / "km")
    serving.save_model(fitted_kmeans, d, version=1, name="km")
    return d


# ----------------------------------------------------------------------
# model codec: save -> hot-load -> predict equivalence grid
# ----------------------------------------------------------------------
class TestModelCodec:
    @pytest.mark.parametrize("kind", ALL_KINDS)
    def test_hot_load_predict_bitwise(self, kind, tmp_path):
        est = _fit(kind)
        d = str(tmp_path / kind)
        serving.save_model(est, d, version=1)
        xt = ht.array(PTS[:16], split=None)
        ref = model_io.infer(est, xt).numpy()
        reg = serving.ModelRegistry()
        reg.load(kind, d)
        got = model_io.infer(reg.get(kind), xt).numpy()
        assert got.dtype == ref.dtype
        assert np.array_equal(ref, got), f"{kind} restored predictions differ"

    @pytest.mark.parametrize("kind", ALL_KINDS)
    def test_cross_world_restore_bitwise(self, kind, tmp_path):
        """Fitted at world P (the test mesh), served at world Q != P."""
        est = _fit(kind)
        d = str(tmp_path / kind)
        serving.save_model(est, d, version=1)
        ref = model_io.infer(est, ht.array(PTS[:16], split=None)).numpy()
        w = ht.get_comm()
        q = 3 if w.size != 3 else 2
        c3 = w.reshape(q)
        before = tm.counter("checkpoint.crossworld_restores").value
        reg = serving.ModelRegistry(comm=c3)
        reg.load(kind, d)
        assert tm.counter("checkpoint.crossworld_restores").value == before + 1
        got = model_io.infer(
            reg.get(kind), ht.array(PTS[:16], split=None, comm=c3)
        ).numpy()
        assert np.array_equal(ref, got), f"{kind} cross-world predictions differ"

    def test_unfitted_estimator_refused(self):
        with pytest.raises(model_io.NotFittedError):
            model_io.export_state(ht.cluster.KMeans(n_clusters=2))

    def test_unsupported_estimator_refused(self):
        with pytest.raises(TypeError, match="supported estimator kinds"):
            model_io.export_state(object())

    def test_non_model_checkpoint_refused(self, tmp_path):
        ck = Checkpointer(str(tmp_path))
        ck.save(0, {"not": "a model"})
        with pytest.raises(ValueError, match="serving model document"):
            serving.ModelRegistry().load("x", str(tmp_path))

    def test_metadata_written(self, kmeans_dir):
        ck = Checkpointer(kmeans_dir)
        meta = ck.metadata(1)
        assert meta["kind"] == "KMeans" and meta["name"] == "km"


# ----------------------------------------------------------------------
# registry: versions, promote/rollback, async load, template validation
# ----------------------------------------------------------------------
class TestRegistry:
    def test_versions_promote_rollback(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        est2 = _fit("KMedians")
        serving.save_model(est2, d, version=2)
        reg = serving.ModelRegistry()
        assert reg.load("m", d, version=1) == 1
        assert reg.active_version("m") == 1
        assert reg.load("m", d, version=2) == 2  # load+activate
        assert reg.active_version("m") == 2
        assert type(reg.get("m")).__name__ == "KMedians"
        assert reg.rollback("m") == 1
        assert type(reg.get("m")).__name__ == "KMeans"
        reg.promote("m", 2)
        assert reg.active_version("m") == 2
        listing = reg.models()["m"]
        assert listing["active"] == 2 and set(listing["versions"]) == {"1", "2"}

    def test_canary_load_without_activation(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        serving.save_model(fitted_kmeans, d, version=2)
        reg = serving.ModelRegistry()
        reg.load("m", d, version=1)
        reg.load("m", d, version=2, activate=False)
        assert reg.active_version("m") == 1  # canary resident, not active
        reg.promote("m", 2)
        assert reg.active_version("m") == 2

    def test_unload_active_refused(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        reg = serving.ModelRegistry()
        reg.load("m", d)
        with pytest.raises(ValueError, match="active"):
            reg.unload("m", 1)
        reg.unload("m")  # whole model is fine
        with pytest.raises(KeyError):
            reg.get("m")

    def test_template_validation_raises_reshape_error(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        bad = model_io.export_state(fitted_kmeans)
        bad["state"] = {"cluster_centers": np.zeros((7, 99), np.float32)}
        with pytest.raises(ReshapeError):
            serving.ModelRegistry().load("m", d, template=bad)

    def test_async_load_swaps_atomically(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        reg = serving.ModelRegistry()
        handle = reg.load_async("m", d)
        assert handle.wait(30) == 1
        assert reg.active_version("m") == 1
        reg.close()

    def test_async_load_error_surfaces_and_old_version_serves(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        reg = serving.ModelRegistry()
        reg.load("m", d)
        handle = reg.load_async("m", str(tmp_path / "missing"))
        with pytest.raises(FileNotFoundError):
            handle.wait(30)
        # the pending error also re-raises at the next close/wait ...
        with pytest.raises(FileNotFoundError):
            reg.close()
        # ... and the active version never stopped serving
        assert reg.active_version("m") == 1
        model_io.infer(reg.get("m"), ht.array(PTS[:4], split=None))

    def test_load_fault_site_scripted(self, tmp_path, fitted_kmeans):
        d = str(tmp_path / "m")
        serving.save_model(fitted_kmeans, d, version=1)
        reg = serving.ModelRegistry()
        reg.load("m", d)
        with faults.fault_plan({"serve.load": [{"at": 0, "kind": "permanent"}]}):
            with pytest.raises(Exception):
                reg.load("m", d)
        assert reg.active_version("m") == 1  # survivor keeps serving


# ----------------------------------------------------------------------
# batch buckets
# ----------------------------------------------------------------------
class TestBatchBucket:
    def test_padding_grid(self):
        assert [dispatch.batch_bucket(n) for n in (1, 2, 3, 5, 8, 9, 33)] == [
            1, 2, 4, 8, 8, 16, 64,
        ]

    def test_cap_is_a_bucket(self):
        assert dispatch.batch_bucket(40, cap=48) == 48
        assert dispatch.batch_bucket(48, cap=48) == 48
        assert dispatch.batch_bucket(3, cap=48) == 4

    def test_bad_inputs(self):
        with pytest.raises(ValueError):
            dispatch.batch_bucket(0)
        with pytest.raises(ValueError):
            dispatch.batch_bucket(65, cap=64)


# ----------------------------------------------------------------------
# coalescer
# ----------------------------------------------------------------------
class TestCoalescer:
    def _echo_batcher(self, max_batch=32, max_delay_s=0.05, calls=None):
        def infer(rows):
            if calls is not None:
                calls.append(rows.shape[0])
            return rows * 2.0

        return ModelBatcher("echo", infer, max_batch=max_batch, max_delay_s=max_delay_s)

    def test_concurrent_requests_coalesce_and_scatter(self):
        calls = []
        b = self._echo_batcher(calls=calls)
        results = {}

        def client(i):
            rows = np.full((1 + i % 3, 4), float(i), np.float32)
            results[i] = (rows, b.submit(rows, timeout=30))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.close()
        for rows, out in results.values():
            assert np.array_equal(out, rows * 2.0)  # each caller got ITS slice
        total_rows = sum(r.shape[0] for r, _ in results.values())
        assert sum(calls) >= total_rows  # bucket padding may add rows
        assert len(calls) < 12  # genuinely coalesced

    def test_batches_are_bucket_padded(self):
        calls = []
        b = self._echo_batcher(calls=calls, max_delay_s=0.0)
        b.submit(np.ones((3, 4), np.float32), timeout=30)
        b.submit(np.ones((5, 4), np.float32), timeout=30)
        b.close()
        assert all((c & (c - 1)) == 0 for c in calls), calls  # powers of two

    def test_inference_error_delivered_to_all_waiters(self):
        def boom(rows):
            raise RuntimeError("kaboom")

        b = ModelBatcher("bad", boom, max_batch=16, max_delay_s=0.0)
        with pytest.raises(RuntimeError, match="kaboom"):
            b.submit(np.ones((2, 2), np.float32), timeout=30)
        assert b.alive()  # the batcher thread survived the error
        b.close()

    def test_batch_fault_site_scripted(self):
        b = self._echo_batcher()
        with faults.fault_plan({"serve.batch": [{"at": 0, "kind": "transient"}]}):
            with pytest.raises(OSError):
                b.submit(np.ones((1, 2), np.float32), timeout=30)
        # next batch is clean
        out = b.submit(np.ones((1, 2), np.float32), timeout=30)
        assert np.array_equal(out, np.full((1, 2), 2.0, np.float32))
        b.close()

    def test_oversized_request_rejected(self):
        b = self._echo_batcher(max_batch=8)
        with pytest.raises(ValueError, match="max batch"):
            b.submit(np.ones((9, 2), np.float32))
        b.close()

    def test_submit_after_close_raises(self):
        b = self._echo_batcher()
        b.close()
        with pytest.raises(RuntimeError, match="closed"):
            b.submit(np.ones((1, 2), np.float32))

    def test_close_drains_queued_requests(self):
        b = self._echo_batcher(max_delay_s=5.0)  # long tick: requests queue up
        out = {}
        t = threading.Thread(
            target=lambda: out.setdefault("r", b.submit(np.ones((2, 2), np.float32), timeout=30))
        )
        t.start()
        time.sleep(0.05)
        b.close()  # must answer the queued request, not strand it
        t.join(30)
        assert "r" in out and np.array_equal(out["r"], np.full((2, 2), 2.0, np.float32))


# ----------------------------------------------------------------------
# admission control
# ----------------------------------------------------------------------
class TestAdmission:
    def test_token_bucket_refill_math(self):
        tb = TokenBucket(rate=10.0, burst=5.0)
        now = time.monotonic()
        assert tb.take(5, now) == 0.0  # burst spent
        wait = tb.take(1, now)
        assert wait == pytest.approx(0.1, rel=1e-6)  # 1 token @ 10/s
        assert tb.take(1, now + 0.2) == 0.0  # refilled

    def test_unlimited_default(self):
        tb = TokenBucket(rate=0.0, burst=1.0)
        assert all(tb.take(100) == 0.0 for _ in range(10))

    def test_quota_shed_with_retry_after(self):
        ac = AdmissionController(max_depth=100)
        ac.set_quota("t", rate=1.0, burst=2.0)
        ac.admit("t", 2)
        with pytest.raises(OverloadedError) as ei:
            ac.admit("t", 2)
        assert ei.value.cause == "quota" and ei.value.retry_after_s > 0
        assert ei.value.tenant == "t"

    def test_queue_depth_shed_and_release(self):
        ac = AdmissionController(max_depth=4)
        ac.admit("a", 3)
        with pytest.raises(OverloadedError) as ei:
            ac.admit("b", 2)
        assert ei.value.cause == "queue"
        ac.release(3)
        ac.admit("b", 2)  # capacity came back
        assert ac.depth() == 2

    def test_tenants_are_isolated(self):
        ac = AdmissionController(max_depth=1000)
        ac.set_quota("cheap", rate=0.001, burst=1.0)
        ac.admit("cheap", 1)
        with pytest.raises(OverloadedError):
            ac.admit("cheap", 1)
        for _ in range(20):  # the default (unlimited) tenant is unaffected
            ac.admit("rich", 1)


# ----------------------------------------------------------------------
# the composed service
# ----------------------------------------------------------------------
class TestService:
    def test_predict_matches_direct(self, kmeans_dir, fitted_kmeans):
        with serving.InferenceService(max_delay_ms=0.5) as svc:
            svc.load("km", kmeans_dir)
            got = svc.predict("km", PTS[:7])
            ref = model_io.infer(
                fitted_kmeans, ht.array(np.concatenate([PTS[:7], np.zeros((1, 6), np.float32)]), split=None)
            ).numpy()[:7]
            assert np.array_equal(got, ref)

    def test_single_row_request(self, kmeans_dir):
        with serving.InferenceService(max_delay_ms=0.5) as svc:
            svc.load("km", kmeans_dir)
            out = svc.predict("km", PTS[0])
            assert out.shape == (1,)

    def test_steady_state_zero_new_compiles(self, kmeans_dir):
        with serving.InferenceService(max_delay_ms=0.5, max_batch=64) as svc:
            svc.load("km", kmeans_dir)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", PTS[:b])
            s0 = dispatch.cache_stats()
            for n in (3, 7, 1, 12, 30, 64, 5, 9, 17, 33):
                svc.predict("km", PTS[:n])
            s1 = dispatch.cache_stats()
            assert s1["misses"] == s0["misses"], "steady-state serving compiled"
            assert s1["hits"] > s0["hits"]

    def test_hot_swap_promote_rollback_zero_downtime(self, tmp_path):
        km = _fit("KMeans")
        d = str(tmp_path / "m")
        serving.save_model(km, d, version=1)
        est2 = _fit("PCA")
        serving.save_model(est2, d, version=2)
        with serving.InferenceService(max_delay_ms=0.5) as svc:
            svc.load("m", d, version=1)
            out1 = svc.predict("m", PTS[:4])
            assert out1.dtype.kind == "i"  # labels
            svc.load("m", d, version=2)  # hot swap to the PCA
            out2 = svc.predict("m", PTS[:4])
            assert out2.dtype.kind == "f" and out2.shape == (4, 3)  # transform
            svc.registry.rollback("m")
            out3 = svc.predict("m", PTS[:4])
            assert np.array_equal(out3, out1)

    def test_unknown_model_keyerror(self, kmeans_dir):
        with serving.InferenceService() as svc:
            with pytest.raises(KeyError, match="unknown model"):
                svc.predict("nope", PTS[:2])

    def test_quota_shed_does_not_block_others(self, kmeans_dir):
        with serving.InferenceService(max_delay_ms=0.5) as svc:
            svc.load("km", kmeans_dir)
            svc.set_quota("cheap", rate=0.001, burst=2.0)
            shed_before = tm.counter("serving.shed_quota").value
            svc.predict("km", PTS[:2], tenant="cheap")
            with pytest.raises(OverloadedError):
                svc.predict("km", PTS[:2], tenant="cheap")
            assert tm.counter("serving.shed_quota").value == shed_before + 1
            for _ in range(3):  # in-quota tenant unaffected
                svc.predict("km", PTS[:4], tenant="rich")

    def test_latency_histogram_populated(self, kmeans_dir):
        with serving.InferenceService(max_delay_ms=0.5) as svc:
            svc.load("km", kmeans_dir)
            before = tm.histogram("serving.latency_ms").count
            svc.predict("km", PTS[:2])
            assert tm.histogram("serving.latency_ms").count == before + 1


# ----------------------------------------------------------------------
# HTTP surface + the route-registry hook
# ----------------------------------------------------------------------
@pytest.fixture
def http_service(kmeans_dir):
    tserver.stop_server()
    svc = serving.InferenceService(max_delay_ms=0.5)
    svc.load("km", kmeans_dir)
    url = svc.serve(0)
    yield svc, url
    svc.close()
    tserver.stop_server()


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, json.loads(r.read() or b"null"), dict(r.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else None), dict(e.headers)


def _post(url, doc, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, json.loads(r.read() or b"null"), dict(r.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else None), dict(e.headers)


class TestHTTP:
    def test_models_listing(self, http_service):
        _, url = http_service
        code, doc, _ = _get(f"{url}/v1/models")
        assert code == 200
        assert doc["models"]["km"]["active"] == 1
        assert doc["models"]["km"]["versions"]["1"]["kind"] == "KMeans"

    def test_predict_roundtrip(self, http_service, fitted_kmeans):
        svc, url = http_service
        code, doc, _ = _post(f"{url}/v1/predict", {"model": "km", "inputs": PTS[:3].tolist()})
        assert code == 200
        assert doc["model"] == "km" and doc["version"] == 1 and doc["n"] == 3
        direct = svc.predict("km", PTS[:3])
        assert np.array_equal(np.asarray(doc["predictions"]), direct)

    def test_predict_unknown_model_404(self, http_service):
        _, url = http_service
        code, doc, _ = _post(f"{url}/v1/predict", {"model": "nope", "inputs": [[1.0] * 6]})
        assert code == 404 and "unknown model" in doc["error"]

    def test_predict_bad_payload_400(self, http_service):
        _, url = http_service
        code, _, _ = _post(f"{url}/v1/predict", {"inputs": [[1.0]]})
        assert code == 400
        req = urllib.request.Request(
            f"{url}/v1/predict", data=b"not json", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400

    def test_over_quota_429_with_retry_after(self, http_service):
        svc, url = http_service
        svc.set_quota("cheap", rate=0.001, burst=2.0)
        body = {"model": "km", "inputs": PTS[:2].tolist(), "tenant": "cheap"}
        code, _, _ = _post(f"{url}/v1/predict", body)
        assert code == 200
        code, doc, headers = _post(f"{url}/v1/predict", body)
        assert code == 429
        assert doc["cause"] == "quota"
        assert float(headers["Retry-After"]) > 0
        # in-quota traffic still lands
        code, _, _ = _post(
            f"{url}/v1/predict", {"model": "km", "inputs": PTS[:2].tolist()}
        )
        assert code == 200

    def test_per_model_healthz(self, http_service):
        _, url = http_service
        code, doc, _ = _get(f"{url}/v1/models/km/healthz")
        assert code == 200 and doc["status"] in ("ok", "idle") and doc["version"] == 1
        code, _, _ = _get(f"{url}/v1/models/ghost/healthz")
        assert code == 404

    def test_builtin_routes_still_served(self, http_service):
        _, url = http_service
        assert _get(f"{url}/healthz")[0] in (200, 503)
        r = urllib.request.urlopen(f"{url}/metrics", timeout=10)
        assert b"serving" in r.read()


class TestRouteRegistry:
    def teardown_method(self):
        tserver.unregister_route("/echo/")
        tserver.unregister_route("/echo/deep/")
        tserver.stop_server()

    def test_register_dispatch_unregister(self):
        tserver.stop_server()
        hits = []

        def handler(method, path, body):
            hits.append((method, path, body))
            return 200, "text/plain", "pong"

        tserver.register_route("/echo/", handler)
        srv = tserver.start_server(0)
        r = urllib.request.urlopen(f"{srv.url}/echo/x", timeout=10)
        assert r.read() == b"pong"
        req = urllib.request.Request(f"{srv.url}/echo/x", data=b"hi", method="POST")
        urllib.request.urlopen(req, timeout=10)
        assert ("GET", "/echo/x", None) in hits and ("POST", "/echo/x", b"hi") in hits
        tserver.unregister_route("/echo/")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{srv.url}/echo/x", timeout=10)
        assert ei.value.code == 404

    def test_longest_prefix_wins(self):
        tserver.stop_server()
        tserver.register_route("/echo/", lambda m, p, b: (200, "text/plain", "shallow"))
        tserver.register_route("/echo/deep/", lambda m, p, b: (200, "text/plain", "deep"))
        srv = tserver.start_server(0)
        assert urllib.request.urlopen(f"{srv.url}/echo/deep/x", timeout=10).read() == b"deep"
        assert urllib.request.urlopen(f"{srv.url}/echo/y", timeout=10).read() == b"shallow"
        assert tserver.registered_routes()[0] == "/echo/deep/"

    def test_routes_survive_server_restart(self):
        tserver.stop_server()
        tserver.register_route("/echo/", lambda m, p, b: (200, "text/plain", "pong"))
        srv = tserver.start_server(0)
        assert urllib.request.urlopen(f"{srv.url}/echo/", timeout=10).read() == b"pong"
        tserver.stop_server()
        tserver.stop_server()  # close() stays idempotent
        srv2 = tserver.start_server(0)
        assert urllib.request.urlopen(f"{srv2.url}/echo/", timeout=10).read() == b"pong"

    def test_handler_error_is_500_and_server_survives(self):
        tserver.stop_server()

        def bad(method, path, body):
            raise RuntimeError("handler bug")

        tserver.register_route("/echo/", bad)
        srv = tserver.start_server(0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{srv.url}/echo/", timeout=10)
        assert ei.value.code == 500
        assert urllib.request.urlopen(f"{srv.url}/metrics", timeout=10).status == 200

    def test_bad_prefix_rejected(self):
        with pytest.raises(ValueError):
            tserver.register_route("echo", lambda m, p, b: (200, "t", ""))


# ----------------------------------------------------------------------
# kill-and-restore: a model fitted at world P serves at world Q
# ----------------------------------------------------------------------
_FIT_AT_P_SOURCE = """
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import heat_tpu as ht
from heat_tpu import serving

rng = np.random.default_rng(7)
pts = rng.standard_normal((96, 5)).astype(np.float32)
x = ht.array(pts, split=0)
km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=6, random_state=1).fit(x)
d = sys.argv[1]
serving.save_model(km, d, version=3, name="km4")
preds = serving.model_io.infer(km, ht.array(pts[:24], split=None)).numpy()
np.save(os.path.join(d, "preds.npy"), preds)
np.save(os.path.join(d, "pts.npy"), pts)
assert ht.get_comm().size == 4
os._exit(0)  # hard exit: the model store must already be durable
"""


class TestCrossWorldServing:
    def test_model_fitted_at_p_serves_at_q(self, tmp_path):
        d = str(tmp_path / "store")
        os.makedirs(d)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _FIT_AT_P_SOURCE, d],
            capture_output=True, text=True, env=env, timeout=280,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        pts = np.load(os.path.join(d, "pts.npy"))
        ref = np.load(os.path.join(d, "preds.npy"))
        ck = Checkpointer(d)
        assert ck.world_size(3) == 4  # fitted at world P=4
        with serving.InferenceService(max_delay_ms=0.5) as svc:  # serves at Q=8
            v = svc.load("km4", d)
            assert v == 3
            rec = svc.registry.record("km4")
            assert rec["world_size_written"] == 4
            assert rec["world_size_serving"] == ht.get_comm().size != 4
            got = np.concatenate(
                [svc.predict("km4", pts[i : i + 8]) for i in range(0, 24, 8)]
            )
            assert np.array_equal(got, ref)
            # and the /healthz doc reports the cross-world provenance
            health = svc.model_health("km4")
            assert health["world_size_written"] == 4
            assert health["healthy"]
