"""Streaming continuous learning (ISSUE 17).

The acceptance properties: stream sources replay bitwise from any
offset (segment log durable + crash-safe, synthetic pure), the consumer
cuts fixed-size windows whose sequence is a function of the committed
offset alone (transient read faults absorbed, key-distribution drift
triggers a windowed rebalance), the prefetch pipeline releases an
UNBOUNDED stream head without draining it, the online fits are
deterministic and pause (not converge) on a dry head, and the
drift-triggered refresh driver closes the loop: alert fires -> re-fit
-> canary with a fresh baseline -> alert resolves -> decision plane
auto-promotes, with zero failed client requests under live traffic.
"""

import os
import threading
import time

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.resilience.errors import ChecksumError, DivergenceError
from heat_tpu.resilience.faults import fault_plan
from heat_tpu.serving import canary as cn
from heat_tpu.streaming import (
    FileSegmentLog,
    RefreshDriver,
    StreamConsumer,
    StreamingKMeans,
    StreamingLasso,
    StreamingPCA,
    SyntheticStream,
)
from heat_tpu.telemetry import alerts as talerts
from heat_tpu.telemetry import sketch as tsketch
from heat_tpu.utils.data import DataLoader
from heat_tpu.utils.data.prefetch import prefetch_to_device


@pytest.fixture(autouse=True)
def _clean_serving_state():
    cn.reset_canary_state()
    talerts.clear_alerts()
    tsketch.SKETCHES.clear()
    yield
    cn.reset_canary_state()
    talerts.clear_alerts()
    tsketch.SKETCHES.clear()


# ----------------------------------------------------------------------
# sources
# ----------------------------------------------------------------------
class TestFileSegmentLog:
    def test_append_read_replay(self, tmp_path):
        log = FileSegmentLog(str(tmp_path), segment_rows=100)
        rows = np.random.default_rng(0).standard_normal((350, 4)).astype(np.float32)
        assert log.append(rows) == 350
        assert log.size == 350 and log.n_features == 4
        # reads span segment boundaries and replay bitwise
        for off, n in ((0, 350), (50, 200), (99, 2), (340, 100)):
            got = log.read(off, n)
            want = rows[off : off + n]
            assert np.array_equal(got, want)
        assert log.read(350, 64).shape == (0, 4)  # at the head: empty

    def test_cross_instance_tail(self, tmp_path):
        """A reader in another process (modeled: another instance) sees
        segments committed after its first scan — the producer/consumer
        split the refresh driver and bench rely on."""
        writer = FileSegmentLog(str(tmp_path), segment_rows=64)
        reader = FileSegmentLog(str(tmp_path), segment_rows=64)
        a = np.full((64, 3), 1.0, np.float32)
        b = np.full((64, 3), 2.0, np.float32)
        writer.append(a)
        assert np.array_equal(reader.read(0, 64), a)
        writer.append(b)  # committed AFTER the reader's scan
        assert np.array_equal(reader.read(64, 64), b)
        assert reader.size == 128

    def test_torn_segment_never_visible(self, tmp_path):
        """A file without the atomic-rename commit (a crashed producer's
        temp) is invisible; a corrupted committed segment raises instead
        of returning garbage."""
        log = FileSegmentLog(str(tmp_path), segment_rows=64)
        log.append(np.zeros((64, 2), np.float32))
        # a crashed producer's staging file: wrong name pattern -> ignored
        (tmp_path / "seg-000000000064-00000064.npy.tmp-x").write_bytes(b"torn")
        assert log.size == 64
        # corrupt the committed segment payload -> checksum mismatch
        seg = next(p for p in tmp_path.iterdir() if p.name.endswith(".npy"))
        data = bytearray(seg.read_bytes())
        data[-1] ^= 0xFF
        seg.write_bytes(bytes(data))
        with pytest.raises(ChecksumError):
            FileSegmentLog(str(tmp_path)).read(0, 64)

    def test_validation(self, tmp_path):
        log = FileSegmentLog(str(tmp_path))
        with pytest.raises(ValueError):
            log.append(np.zeros(8, np.float32))  # 1-D
        with pytest.raises(ValueError):
            log.read(-1, 8)
        with pytest.raises(ValueError):
            FileSegmentLog(str(tmp_path), segment_rows=0)


class TestSyntheticStream:
    def test_replay_any_offset(self):
        syn = SyntheticStream(n_features=3, seed=7, block_rows=64)
        assert np.array_equal(syn.read(100, 300), syn.read(100, 300))
        # window size / read order never changes the bytes
        whole = syn.read(0, 512)
        parts = np.concatenate([syn.read(o, 128) for o in (0, 128, 256, 384)])
        assert np.array_equal(whole, parts)

    def test_drift_at_shifts_the_tail(self):
        syn = SyntheticStream(n_features=2, seed=1, block_rows=32, drift_at=100,
                              drift_shift=5.0)
        clean = SyntheticStream(n_features=2, seed=1, block_rows=32)
        rows = syn.read(0, 200)
        base = clean.read(0, 200)
        assert np.array_equal(rows[:100], base[:100])
        assert np.allclose(rows[100:], base[100:] + 5.0)

    def test_total_rows_bounds_the_head(self):
        syn = SyntheticStream(n_features=2, total_rows=100, block_rows=32)
        assert syn.size == 100
        assert syn.read(80, 64).shape == (20, 2)
        assert syn.read(100, 64).shape == (0, 2)


# ----------------------------------------------------------------------
# the consumer
# ----------------------------------------------------------------------
class TestStreamConsumer:
    def test_fixed_windows_and_head(self, tmp_path):
        log = FileSegmentLog(str(tmp_path), segment_rows=50)
        rows = np.random.default_rng(3).standard_normal((150, 3)).astype(np.float32)
        log.append(rows)
        with StreamConsumer(log, window_rows=64, prefetch=2) as cons:
            off0, w0 = cons.next_window(0)
            off1, w1 = cons.next_window(64)
            assert (off0, off1) == (0, 64)
            assert np.array_equal(np.asarray(w0), rows[:64])
            assert np.array_equal(np.asarray(w1), rows[64:128])
            # 22 rows at the head: a partial window is NEVER consumed
            assert cons.next_window(128) is None
            # the producer lands more rows; the same offset now yields
            log.append(np.ones((64, 3), np.float32))
            off2, w2 = cons.next_window(128)
            assert off2 == 128 and np.asarray(w2).shape == (64, 3)

    def test_reseek_replays_bitwise(self):
        syn = SyntheticStream(n_features=4, seed=5, block_rows=32, total_rows=320)
        with StreamConsumer(syn, window_rows=32) as cons:
            seq = [np.asarray(cons.next_window(32 * i)[1]) for i in range(4)]
            # a resume-style seek back to offset 64 replays window 2 bitwise
            _, again = cons.next_window(64)
            assert np.array_equal(np.asarray(again), seq[2])

    def test_transient_read_fault_absorbed(self):
        """A scripted transient at ``stream.read`` retries inside the io
        policy — the window arrives, bitwise-identical."""
        syn = SyntheticStream(n_features=2, seed=9, block_rows=64, total_rows=128)
        want = syn.read(0, 64)
        with fault_plan({"stream.read": [{"at": 1, "kind": "transient"}]}) as inj:
            with StreamConsumer(syn, window_rows=64, prefetch=1) as cons:
                _, w = cons.next_window(0)
        assert np.array_equal(np.asarray(w), want)
        assert inj.injected.get("stream.read"), "the fault must have fired"

    def test_key_drift_triggers_reshard(self):
        """A sustained key-distribution shift past the PSI knob flags
        exactly one reshard; ``maybe_reshard`` rebalances the caller's
        split array and clears the flag."""
        syn = SyntheticStream(n_features=3, seed=2, block_rows=64, total_rows=640,
                              drift_at=320, drift_shift=100.0)
        with StreamConsumer(syn, window_rows=64, reshard_psi=0.25) as cons:
            seen = 0
            for i in range(10):
                assert cons.next_window(64 * i) is not None
                seen += 1
            assert cons.reshard_events == 1, "one sustained shift = one reshard"
            x = ht.array(np.random.default_rng(0).standard_normal((64, 3)), split=0)
            assert cons.maybe_reshard(x) is True
            assert cons.maybe_reshard(x) is False  # flag cleared
            assert seen == 10

    def test_no_reshard_on_stationary_keys(self):
        syn = SyntheticStream(n_features=3, seed=2, block_rows=64, total_rows=640)
        with StreamConsumer(syn, window_rows=64) as cons:
            for i in range(10):
                cons.next_window(64 * i)
            assert cons.reshard_events == 0
            assert cons.maybe_reshard() is False


# ----------------------------------------------------------------------
# prefetch shutdown on unbounded iterators (satellite: DataLoader/
# prefetch_to_device close must not drain an infinite stream head)
# ----------------------------------------------------------------------
class TestPrefetchClose:
    def test_close_releases_never_ending_generator(self):
        pulled = {"n": 0}
        closed = threading.Event()

        def forever():
            try:
                i = 0
                while True:  # a live stream head: iterating never ends
                    pulled["n"] += 1
                    yield np.full((4, 2), i, np.float32)
                    i += 1
            finally:
                closed.set()

        it = prefetch_to_device(forever(), size=3)
        first = next(it)
        assert np.asarray(first).shape == (4, 2)
        t0 = time.monotonic()
        it.close()  # must return promptly, NOT drain the stream
        assert time.monotonic() - t0 < 1.0
        assert closed.is_set(), "close() must release the generator (finally ran)"
        # bounded look-ahead, not a drain: first + at most size staged
        assert pulled["n"] <= 1 + 3 + 1
        with pytest.raises(StopIteration):
            next(it)
        it.close()  # idempotent

    def test_context_manager_releases_on_exit(self):
        closed = threading.Event()

        def forever():
            try:
                while True:
                    yield np.zeros((2, 2), np.float32)
            finally:
                closed.set()

        with prefetch_to_device(forever(), size=2) as it:
            next(it)
        assert closed.is_set()

    def test_dataloader_close_releases_prefetched_epoch(self):
        x = ht.array(np.random.default_rng(1).standard_normal((64, 3)).astype(np.float32))
        dl = DataLoader(x, batch_size=8, shuffle=False, prefetch=2)
        it = iter(dl)
        next(it)
        dl.close()  # mid-epoch release: no drain, no error
        dl.close()  # idempotent
        # a fresh epoch still works after close
        batches = list(iter(dl))
        assert len(batches) == 8


# ----------------------------------------------------------------------
# online fits
# ----------------------------------------------------------------------
def _clustered_rows(n, rng, shift=0.0, centers=None):
    """Well-separated clusters with CYCLING labels, so the first k rows
    cover every cluster (first-k-rows seeding lands one center each)."""
    centers = centers if centers is not None else np.array(
        [[0.0] * 4, [40.0] * 4, [80.0] * 4], np.float32
    )
    labels = np.arange(n) % len(centers)
    noise = rng.standard_normal((n, 4)).astype(np.float32) * 0.5
    return (centers[labels] + noise + np.float32(shift)).astype(np.float32)


class TestOnlineFits:
    def test_kmeans_deterministic(self):
        def fit():
            syn = SyntheticStream(n_features=4, seed=1, block_rows=64, total_rows=640)
            return StreamingKMeans(n_clusters=4, window_rows=64).fit_stream(syn)

        a, b = fit(), fit()
        assert np.array_equal(a.cluster_centers_, b.cluster_centers_)
        assert a.n_windows_ == 10 and a.offset_ == 640

    def test_pca_deterministic_and_sensible(self):
        def fit():
            syn = SyntheticStream(n_features=5, seed=2, block_rows=32, total_rows=256)
            return StreamingPCA(n_components=2, window_rows=32).fit_stream(syn)

        a, b = fit(), fit()
        assert np.array_equal(a.components_, b.components_)
        est = a.to_estimator()
        evr = np.asarray(est.explained_variance_ratio_._dense())
        assert evr.shape == (2,) and 0.0 < float(evr.sum()) <= 1.0 + 1e-5

    def test_lasso_deterministic(self):
        def fit():
            syn = SyntheticStream(n_features=4, seed=3, block_rows=64, total_rows=640)
            return StreamingLasso(lam=0.01, lr=0.1, window_rows=64).fit_stream(syn)

        a, b = fit(), fit()
        assert np.array_equal(a.theta_, b.theta_)

    def test_pause_resume_bitwise(self, tmp_path):
        """An in-process split fit (4 windows, then resume to the end)
        reproduces the uninterrupted fit bitwise — the offset rides the
        checkpoint, so the window sequence replays identically."""
        def fit(**kw):
            syn = SyntheticStream(n_features=4, seed=1, block_rows=64, total_rows=640)
            km = StreamingKMeans(n_clusters=4, window_rows=64, **kw)
            return km.fit_stream(syn, max_windows=kw.pop("cap", None) if "cap" in kw else None)

        ref = fit()
        d = str(tmp_path / "ck")
        part = StreamingKMeans(n_clusters=4, window_rows=64, commit_every=1,
                               checkpoint_dir=d)
        part.fit_stream(SyntheticStream(n_features=4, seed=1, block_rows=64,
                                        total_rows=640), max_windows=4)
        assert part.n_windows_ == 4
        done = StreamingKMeans(n_clusters=4, window_rows=64, commit_every=1,
                               resume_from=d)
        done.fit_stream(SyntheticStream(n_features=4, seed=1, block_rows=64,
                                        total_rows=640))
        assert done.n_windows_ == 10
        assert np.array_equal(done.cluster_centers_, ref.cluster_centers_)

    def test_dry_head_pauses_not_converges(self, tmp_path):
        """A dry stream head checkpoints ``converged=False``: the same
        directory keeps consuming when the producer appends more."""
        log = FileSegmentLog(str(tmp_path / "log"), segment_rows=64)
        rng = np.random.default_rng(0)
        log.append(_clustered_rows(128, rng))
        d = str(tmp_path / "ck")
        kw = dict(n_clusters=3, window_rows=64, commit_every=1,
                  checkpoint_dir=d, resume_from=d)
        km = StreamingKMeans(**kw).fit_stream(log)
        assert km.n_windows_ == 2  # paused at the head, not converged
        log.append(_clustered_rows(192, rng))
        km2 = StreamingKMeans(**kw).fit_stream(log)
        assert km2.n_windows_ == 5 and km2.offset_ == 320

    def test_divergence_guarded(self, tmp_path):
        log = FileSegmentLog(str(tmp_path), segment_rows=64)
        rows = _clustered_rows(192, np.random.default_rng(0))
        rows[100] = np.nan  # a poisoned window
        log.append(rows)
        with pytest.raises(DivergenceError):
            StreamingKMeans(n_clusters=3, window_rows=64).fit_stream(log)

    def test_servable_conversions(self):
        syn = SyntheticStream(n_features=4, seed=1, block_rows=64, total_rows=320)
        km = StreamingKMeans(n_clusters=3, window_rows=64).fit_stream(syn)
        q = ht.array(syn.read(0, 16))
        labels = np.asarray(km.to_estimator().predict(q)._dense())
        assert labels.shape[0] == 16 and set(labels.ravel()) <= {0, 1, 2}

        syn_l = SyntheticStream(n_features=3, seed=4, block_rows=64, total_rows=320)
        las = StreamingLasso(lam=0.01, lr=0.1, window_rows=64).fit_stream(syn_l)
        ql = ht.array(syn_l.read(0, 8)[:, :-1])
        assert np.asarray(las.to_estimator().predict(ql)._dense()).shape == (8, 1)


# ----------------------------------------------------------------------
# drift-triggered refresh
# ----------------------------------------------------------------------
def _seed_streamed_model(tmp_path, name="km"):
    """v1: a streamed KMeans over pre-drift rows, saved WITH a baseline
    from its recent training window; returns (log, ckpt dir, model dir)."""
    log = FileSegmentLog(str(tmp_path / "log"), segment_rows=256)
    log.append(_clustered_rows(64 * 8, np.random.default_rng(0)))
    ck = str(tmp_path / "ck")
    km = StreamingKMeans(n_clusters=3, window_rows=64, commit_every=1,
                         checkpoint_dir=ck, resume_from=ck).fit_stream(log)
    sk = tsketch.ModelSketch(name, 4)
    sk.update(km.recent_window_)
    d = str(tmp_path / "models")
    serving.save_model(km.to_estimator(), d, version=1, name=name,
                       baseline=sk.doc())
    return log, ck, d


def _drifted_fitter(log, ck, shift=4.0, windows=6, seed=1):
    """The refresh recipe: append recent (drifted) rows, resume the
    online fit from its own checkpoints — a warm start from the live
    model's centers, so label indices stay aligned."""

    def fitter():
        log.append(_clustered_rows(64 * windows, np.random.default_rng(seed),
                                   shift=shift))
        km = StreamingKMeans(n_clusters=3, window_rows=64, commit_every=1,
                             checkpoint_dir=ck, resume_from=ck)
        return km.fit_stream(log)

    return fitter


class TestRefreshDriver:
    def test_idle_without_drift(self, tmp_path):
        log, ck, d = _seed_streamed_model(tmp_path)
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            svc.load("km", d, version=1)
            drv = RefreshDriver(svc, "km", d, _drifted_fitter(log, ck))
            assert drv.check() == "idle"
            assert svc.registry.canary_version("km") is None
        finally:
            svc.close()

    def test_fire_refresh_promote_resolve_cycle(self, tmp_path):
        """The satellite acceptance cycle: drift fires -> refresh saves
        a canary carrying a FRESH baseline from its recent window -> the
        re-warmed live sketch scores clean, the alert RESOLVES (instead
        of re-firing against the stale baseline) -> the decision plane's
        drift veto clears and the canary auto-promotes."""
        log, ck, d = _seed_streamed_model(tmp_path)
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            svc.load("km", d, version=1)
            svc.canary.fraction = 1.0
            svc.canary.min_rows = 48
            drv = RefreshDriver(svc, "km", d, _drifted_fitter(log, ck))
            rng = np.random.default_rng(99)

            # drifted traffic warms the live sketch past the floor
            for _ in range(30):
                svc.predict("km", _clustered_rows(8, rng, shift=4.0))
            assert drv.check() == "refreshed"
            assert talerts.is_firing("drift:km", labels={"model": "km"})
            assert svc.registry.canary_version("km") == 2
            # a second check while the canary is resident defers to the
            # decision plane instead of stacking refreshes
            assert drv.check() in ("pending", "idle")

            failed = 0
            for _ in range(60):
                try:
                    svc.predict("km", _clustered_rows(8, rng, shift=4.0))
                except Exception:
                    failed += 1
                drv.check()
                if svc.registry.active_version("km") == 2:
                    break
            assert svc.canary.wait_idle(30)
            assert failed == 0
            assert svc.registry.active_version("km") == 2
            assert svc.registry.canary_version("km") is None
            st = cn.status("km")
            assert st["decision"]["action"] == "promoted"
            # the triggering alert stays RESOLVED after promotion
            tsketch.check_drift()
            assert not talerts.is_firing("drift:km", labels={"model": "km"})
            assert drv.refreshes == 1 and drv.last_version == 2
        finally:
            svc.close()

    def test_cooldown_defers(self, tmp_path):
        log, ck, d = _seed_streamed_model(tmp_path)
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            svc.load("km", d, version=1)
            drv = RefreshDriver(svc, "km", d, _drifted_fitter(log, ck),
                                min_interval_s=3600.0)
            rng = np.random.default_rng(5)
            for _ in range(30):
                svc.predict("km", _clustered_rows(8, rng, shift=4.0))
            assert drv.check() == "refreshed"
            # promote the canary out of the slot, re-poison the live
            # sketch: the cooldown (not the canary slot) must defer now
            svc.registry.promote("km", 2)
            tsketch.SKETCHES.set_baseline(
                "km", tsketch.SKETCHES.baseline("km"))
            for _ in range(30):
                svc.predict("km", _clustered_rows(8, rng, shift=-6.0))
            assert drv.check() in ("pending", "idle")
            assert drv.refreshes == 1
        finally:
            svc.close()

    def test_background_poller_lifecycle(self, tmp_path):
        log, ck, d = _seed_streamed_model(tmp_path)
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            svc.load("km", d, version=1)
            with RefreshDriver(svc, "km", d,
                               _drifted_fitter(log, ck)).start(poll_s=0.05) as drv:
                rng = np.random.default_rng(7)
                deadline = time.monotonic() + 30.0
                while drv.refreshes == 0 and time.monotonic() < deadline:
                    svc.predict("km", _clustered_rows(8, rng, shift=4.0))
                assert drv.refreshes >= 1
            assert drv._thread is None  # closed
        finally:
            svc.close()


# ----------------------------------------------------------------------
# e2e: injected drift under LIVE threaded traffic -> refresh -> shadow
# compare -> auto-promote, zero failed client requests
# ----------------------------------------------------------------------
class TestLiveTrafficE2E:
    def test_drift_refresh_promote_under_live_traffic(self, tmp_path):
        log, ck, d = _seed_streamed_model(tmp_path)
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            svc.load("km", d, version=1)
            svc.canary.fraction = 1.0
            svc.canary.min_rows = 48
            drv = RefreshDriver(svc, "km", d, _drifted_fitter(log, ck))

            stop = threading.Event()
            failures, requests = [], [0] * 4

            def client(i):
                rng = np.random.default_rng(100 + i)
                while not stop.is_set():
                    try:
                        out = svc.predict("km", _clustered_rows(8, rng, shift=4.0))
                        assert np.asarray(out).shape[0] == 8
                        requests[i] += 1
                    except Exception as exc:  # lint: allow H501(the assertion IS "no exception escapes predict")
                        failures.append(repr(exc))
                        return

            threads = [threading.Thread(target=client, args=(i,), daemon=True)
                       for i in range(4)]
            for t in threads:
                t.start()
            try:
                deadline = time.monotonic() + 60.0
                promoted = False
                while time.monotonic() < deadline:
                    drv.check()
                    if svc.registry.active_version("km") == 2:
                        promoted = True
                        break
                    time.sleep(0.05)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10.0)
            assert promoted, "the refreshed canary must auto-promote under live traffic"
            assert not failures, f"client requests failed: {failures[:3]}"
            assert min(requests) > 0, "every client thread must have served"
            assert svc.canary.wait_idle(30)
            st = cn.status("km")
            assert st["decision"]["action"] == "promoted"
            tsketch.check_drift()
            assert not talerts.is_firing("drift:km", labels={"model": "km"})
        finally:
            svc.close()
