"""Process-global metrics registry: counters, gauges, bounded histograms.

Before this module the framework had four *disjoint* counter islands —
``core.dispatch.cache_stats()``, ``resilience_stats()``,
``overlap_stats()`` and the ``grad_buckets`` counter inside
``nn.data_parallel`` — each with its own snapshot/reset convention and
none visible in one place.  The reference framework has it worse: zero
in-library observability, with benchmarks instrumented from the outside
by the external ``perun`` monitor (benchmarks/cb/linalg.py:4,7).

This registry is the single home for every named metric in the process:

* :class:`Counter` — monotonically increasing int/float totals
  (``comm.bytes.psum``, ``dispatch.hits``).
* :class:`Gauge` — last-written values (``fit.iter_rate``) or live
  callbacks (``dispatch.cache_size`` reads ``len(_cache)`` on demand).
* :class:`Histogram` — bounded geometric-bucket distributions: p50/p90/
  p99 estimates **without storing samples** (fixed ~12%-wide log-spaced
  buckets; memory is O(buckets touched), never O(observations)), used
  for ``dispatch.compile_ms``.

Every island re-registers its counters here, so one
:func:`snapshot` / :func:`reset` / :func:`dump_json` /
:func:`expose` surface covers the whole stack; the islands' public
``*_stats()`` functions are now thin views over this registry.

All operations are thread-safe (per-metric locks; the overlap layer's
background checkpoint writer and data-loader workers bump counters from
their own threads).  The registry-level name->metric map is guarded by
a lock registered in ``analysis/concurrency.py LOCK_REGISTRY``
(``telemetry.metrics.registry``) — under ``HEAT_TPU_TSAN=1`` the
concurrency sanitizer verifies every cross-thread access holds it; the
per-metric value locks stay unregistered leaf locks (they guard one
scalar each and are never held across another acquire).
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..analysis import tsan as _tsan

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Info",
    "MetricsRegistry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "info",
    "register_dump_section",
    "snapshot",
    "reset",
    "dump_json",
    "expose",
]

#: extra named sections embedded in the ``HEAT_TPU_METRICS_DUMP``
#: atexit JSON beside the metrics snapshot: name -> zero-arg provider
#: (the observatory registers its ledger/watermark/calibration section
#: here).  Registered at import time on the main thread, read only at
#: dump time; a provider failure drops its section, never the dump.
_DUMP_SECTIONS: "Dict[str, Callable[[], Any]]" = {}


def register_dump_section(name: str, provider: Callable[[], Any]) -> None:
    """Attach a named section to every metrics dump (last wins)."""
    _DUMP_SECTIONS[str(name)] = provider

Number = Union[int, float]


def _escape_label(v: str) -> str:
    """OpenMetrics label-value escaping: backslash, double-quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

#: histogram bucket upper bounds: 10**(e/20) for e in [-120, 240] — a
#: geometric ladder from 1e-6 to 1e12 in ~12% steps.  Quantile estimates
#: interpolate inside one bucket, so the worst-case relative error of a
#: reported p50/p90/p99 is half a bucket (~6%) — plenty for wall-time
#: distributions, at a fixed worst-case memory of 361 ints.
_BOUNDS: List[float] = [10.0 ** (e / 20.0) for e in range(-120, 241)]


class Counter:
    """Monotonic named total (int or float increments)."""

    __slots__ = ("name", "doc", "_value", "_lock")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._value: Number = 0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written value, or a live callback evaluated at read time."""

    __slots__ = ("name", "doc", "fn", "_value", "_lock")

    def __init__(self, name: str, doc: str = "", fn: Optional[Callable[[], Number]] = None):
        self.name = name
        self.doc = doc
        self.fn = fn
        self._value: Number = 0.0
        self._lock = threading.Lock()

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> Number:
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:  # lint: allow H501(gauge callback isolation, value degrades to 0)
                return 0.0
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Bounded distribution: geometric buckets, exact count/sum/min/max.

    ``observe(v)`` is O(log buckets); quantiles come from a cumulative
    walk over the (sparse) bucket counts with geometric interpolation
    inside the crossing bucket, clamped to the exact observed [min, max].
    Non-positive observations land in a dedicated low bucket valued at
    the observed minimum (durations are the intended payload; zeros
    happen on sub-resolution clocks).

    ``observe(v, exemplar=trace_id)`` additionally makes the bucket ``v``
    lands in remember that trace id (most recent wins) — an OpenMetrics
    **exemplar**, the link from an aggregate latency bucket back to one
    concrete request retained in the ``/tracez`` tail store.  Exemplars
    cost one dict write per exemplared observation and nothing
    otherwise; :func:`MetricsRegistry.expose` renders histograms that
    carry them in OpenMetrics bucket syntax."""

    __slots__ = ("name", "doc", "_buckets", "_low", "_count", "_sum", "_min",
                 "_max", "_exemplars", "_lock")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._buckets: Dict[int, int] = {}
        self._low = 0  # observations <= 0 (or under the first bound)
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        # bucket index (-1 = low bucket) -> (value, trace_id, unix_ts)
        self._exemplars: Dict[int, Tuple[float, str, float]] = {}
        self._lock = threading.Lock()

    def observe(self, v: Number, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if v <= _BOUNDS[0]:
                ix = -1
                self._low += 1
            else:
                ix = bisect.bisect_left(_BOUNDS, v)
                self._buckets[ix] = self._buckets.get(ix, 0) + 1
            if exemplar is not None:
                self._exemplars[ix] = (v, str(exemplar), time.time())

    def exemplars(self) -> Dict[float, Dict[str, Any]]:
        """Per-bucket exemplars keyed by the bucket's upper bound:
        ``{le: {"value", "trace_id", "ts"}}`` (empty when none were
        recorded)."""
        with self._lock:
            items = dict(self._exemplars)
        return {
            (_BOUNDS[0] if ix < 0 else _BOUNDS[ix]): {
                "value": val, "trace_id": tid, "ts": ts
            }
            for ix, (val, tid, ts) in sorted(items.items())
        }

    def bucket_counts(self) -> Tuple[int, Dict[int, int], int, float]:
        """Cumulative bucket state ``(low, buckets, count, sum)`` under
        one lock acquisition — the sample the SLO monitors' windowed
        burn-rate math diffs between ticks (:mod:`heat_tpu.telemetry.
        slo`).  ``buckets`` maps ladder index -> count; ``low`` counts
        observations at or under the first bound."""
        with self._lock:
            return (self._low, dict(self._buckets), self._count, self._sum)

    def _bucket_rows(self) -> List[Tuple[float, int, Optional[Tuple[float, str, float]]]]:
        """Cumulative ``(le, count, exemplar)`` rows over the touched
        buckets (the OpenMetrics exposition shape)."""
        with self._lock:
            buckets = dict(self._buckets)
            low = self._low
            ex = dict(self._exemplars)
        rows: List[Tuple[float, int, Optional[Tuple[float, str, float]]]] = []
        cum = 0
        if low:
            cum += low
            rows.append((_BOUNDS[0], cum, ex.get(-1)))
        for ix in sorted(buckets):
            cum += buckets[ix]
            rows.append((_BOUNDS[ix], cum, ex.get(ix)))
        return rows

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def min(self) -> Optional[float]:
        with self._lock:
            return self._min if self._count else None

    @property
    def max(self) -> Optional[float]:
        with self._lock:
            return self._max if self._count else None

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]); None when empty.

        The extremes are exact, not bucket estimates: q=0 returns the
        observed minimum and q=1 the observed maximum (the interpolated
        walk would otherwise report a bucket midpoint below the true
        max whenever the top bucket is wide — the edge the SLO windowed
        math must not inherit)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if not self._count:
                return None
            if q == 0.0:
                return self._min
            if q == 1.0:
                return self._max
            target = q * self._count
            seen = self._low
            if seen >= target:
                return self._min
            val = self._max
            for ix in sorted(self._buckets):
                seen += self._buckets[ix]
                if seen >= target:
                    lo = _BOUNDS[ix - 1] if ix > 0 else _BOUNDS[0]
                    hi = _BOUNDS[ix]
                    val = (lo * hi) ** 0.5  # geometric bucket midpoint
                    break
            return min(max(val, self._min), self._max)

    def snapshot(self) -> Dict[str, Any]:
        doc = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.5),
            "p90": self.quantile(0.9),
            "p99": self.quantile(0.99),
        }
        ex = self.exemplars()
        if ex:
            doc["exemplars"] = {f"{le:g}": rec for le, rec in ex.items()}
        return doc

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._low = 0
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")
            self._exemplars.clear()


class Info:
    """Constant build/runtime identity: the OpenMetrics *info* pattern.

    A metric whose payload is its **labels** (version strings, backend,
    device kind) with a constant sample value of 1 — ``build_info`` in
    the exposition joins any scraped series to the binary that produced
    it.  Labels come from a zero-arg provider resolved **lazily on first
    read and cached**: ``build_info`` needs ``jax.devices()``, and
    resolving that at registration time would initialize the backend as
    an import side effect.  :meth:`reset` keeps the cache — identity is
    not a counter."""

    __slots__ = ("name", "doc", "fn", "_labels", "_lock")

    def __init__(self, name: str, doc: str = "",
                 fn: Optional[Callable[[], Dict[str, str]]] = None):
        self.name = name
        self.doc = doc
        self.fn = fn
        self._labels: Optional[Dict[str, str]] = None
        self._lock = threading.Lock()

    def labels(self) -> Dict[str, str]:
        with self._lock:
            if self._labels is None:
                resolved: Dict[str, str] = {}
                if self.fn is not None:
                    try:
                        resolved = {
                            str(k): str(v) for k, v in (self.fn() or {}).items()
                        }
                    except Exception:  # lint: allow H501(label provider isolation, identity degrades to empty)
                        resolved = {}
                self._labels = resolved
            return dict(self._labels)

    @property
    def value(self) -> int:
        return 1

    def reset(self) -> None:
        pass  # identity is constant; nothing to zero


class MetricsRegistry:
    """Name -> metric map with one snapshot/reset/export surface.

    Dotted names form domains (``dispatch.hits``, ``comm.bytes.psum``);
    :meth:`reset` takes a prefix so an island's legacy reset function
    can clear exactly its own metrics."""

    def __init__(self):
        self._metrics: "Dict[str, Union[Counter, Gauge, Histogram]]" = {}
        # re-entrant: a sanitizer finding inside a locked section reports
        # through a telemetry counter, which re-enters this registry
        self._lock = _tsan.register_lock(
            "telemetry.metrics.registry", threading.RLock()
        )

    def _get_or_make(self, name: str, cls, **kwargs):
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry")
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}, "
                    f"not {cls.__name__}"
                )
            return m

    def counter(self, name: str, doc: str = "") -> Counter:
        return self._get_or_make(name, Counter, doc=doc)

    def gauge(self, name: str, doc: str = "", fn: Optional[Callable[[], Number]] = None) -> Gauge:
        g = self._get_or_make(name, Gauge, doc=doc)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, doc: str = "") -> Histogram:
        return self._get_or_make(name, Histogram, doc=doc)

    def info(self, name: str, doc: str = "",
             fn: Optional[Callable[[], Dict[str, str]]] = None) -> Info:
        m = self._get_or_make(name, Info, doc=doc)
        if fn is not None and m.fn is None:
            m.fn = fn
        return m

    def get(self, name: str):
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry", write=False)
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry", write=False)
            return sorted(self._metrics)

    def snapshot(self, include_zero: bool = True) -> Dict[str, Any]:
        """One document of every metric's current value.

        Counters/gauges report their numeric value; histograms report a
        ``{count, sum, min, max, p50, p90, p99}`` sub-document.
        ``include_zero=False`` drops zero counters and empty histograms
        (compact per-config embedding for bench artifacts)."""
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry", write=False)
            items = sorted(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in items:
            if isinstance(m, Histogram):
                if not include_zero and m.count == 0:
                    continue
                out[name] = m.snapshot()
            elif isinstance(m, Info):
                out[name] = m.labels()
            else:
                v = m.value
                if not include_zero and not v:
                    continue
                out[name] = v
        return out

    def reset(self, prefix: Optional[str] = None) -> None:
        """Zero every metric (or only names under ``prefix``).  Callback
        gauges are left alone — their value is derived live."""
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry", write=False)
            items = list(self._metrics.items())
        for name, m in items:
            if prefix is not None and not name.startswith(prefix):
                continue
            if isinstance(m, Gauge) and m.fn is not None:
                continue
            m.reset()

    def dump_json(self, path: str) -> None:
        """Write the full snapshot as JSON through the resilience atomic
        writer (write-temp-fsync-rename + CRC32 sidecar) — the artifact
        the ``HEAT_TPU_METRICS_DUMP`` atexit hook produces for CI
        scraping.  A crash mid-dump can never leave a truncated file,
        and a reader can verify the payload against the sidecar."""
        # lazy import: resilience.faults imports this module at its top
        from ..resilience.atomic import atomic_write

        doc = {"timestamp": time.time(), "pid": os.getpid(), "metrics": self.snapshot()}
        for name, provider in _DUMP_SECTIONS.items():
            try:
                doc[name] = provider()
            except Exception:  # lint: allow H501(a section provider bug drops its section, never the dump)
                doc[name] = None
        with atomic_write(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)

    def expose(self) -> str:
        """Prometheus text exposition of every metric.

        Counters/gauges emit one sample; histograms emit a summary
        (quantile-labeled samples plus ``_sum``/``_count``) — except
        histograms carrying **exemplars**, which emit OpenMetrics
        histogram syntax instead (cumulative ``_bucket{le=...}`` samples
        over the touched buckets, each annotated
        ``# {trace_id="..."} value timestamp`` with the most recent
        trace that landed in it), so a scraper can jump from a latency
        bucket straight to the retained trace in ``/tracez``.  Metric
        names are sanitized to the Prometheus charset with a
        ``heat_tpu_`` namespace prefix.

        The payload ends with the OpenMetrics ``# EOF`` terminator and
        the serving routes send it as ``application/openmetrics-text``:
        exemplar syntax is OpenMetrics, not Prometheus-text 0.0.4, and a
        spec-compliant scraper treats a payload without the terminator
        as torn (exposition hygiene, docs/observability.md)."""
        lines: List[str] = []
        with self._lock:
            _tsan.note_access("telemetry.metrics.registry", write=False)
            items = sorted(self._metrics.items())
        for name, m in items:
            pname = "heat_tpu_" + "".join(
                c if (c.isalnum() or c == "_") else "_" for c in name
            )
            if isinstance(m, Info):
                # the OpenMetrics info pattern: identity in the labels,
                # constant sample value 1
                lines.append(f"# TYPE {pname} gauge")
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(m.labels().items())
                )
                lines.append(f"{pname}{{{labels}}} 1" if labels else f"{pname} 1")
            elif isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {m.value}")
            elif m.exemplars():
                lines.append(f"# TYPE {pname} histogram")
                rows = m._bucket_rows()
                for le, cum, ex in rows:
                    sample = f'{pname}_bucket{{le="{le:g}"}} {cum}'
                    if ex is not None:
                        val, tid, ts = ex
                        sample += f' # {{trace_id="{tid}"}} {val:g} {ts:.3f}'
                    lines.append(sample)
                lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{pname}_sum {m.sum}")
                lines.append(f"{pname}_count {m.count}")
            else:
                lines.append(f"# TYPE {pname} summary")
                for q in (0.5, 0.9, 0.99):
                    v = m.quantile(q)
                    if v is not None:
                        lines.append(f'{pname}{{quantile="{q}"}} {v}')
                lines.append(f"{pname}_sum {m.sum}")
                lines.append(f"{pname}_count {m.count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


#: the process-global registry every subsystem registers into
REGISTRY = MetricsRegistry()


def counter(name: str, doc: str = "") -> Counter:
    """Get-or-create a counter in the global registry."""
    return REGISTRY.counter(name, doc)


def gauge(name: str, doc: str = "", fn: Optional[Callable[[], Number]] = None) -> Gauge:
    """Get-or-create a gauge (optionally callback-backed) in the global registry."""
    return REGISTRY.gauge(name, doc, fn)


def histogram(name: str, doc: str = "") -> Histogram:
    """Get-or-create a bounded histogram in the global registry."""
    return REGISTRY.histogram(name, doc)


def info(name: str, doc: str = "",
         fn: Optional[Callable[[], Dict[str, str]]] = None) -> Info:
    """Get-or-create an info metric (lazy labeled identity) in the
    global registry."""
    return REGISTRY.info(name, doc, fn)


def snapshot(include_zero: bool = True) -> Dict[str, Any]:
    """Snapshot of every registered metric (see :meth:`MetricsRegistry.snapshot`)."""
    return REGISTRY.snapshot(include_zero)


def reset(prefix: Optional[str] = None) -> None:
    """Zero every registered metric, or only names under ``prefix``."""
    REGISTRY.reset(prefix)


def dump_json(path: str) -> None:
    """Write the global registry's snapshot as JSON."""
    REGISTRY.dump_json(path)


def expose() -> str:
    """Prometheus text exposition of the global registry."""
    return REGISTRY.expose()
