"""Sparse split-sweep battery, the analog of the reference's
heat/sparse/tests families (test_arithmetics_csr.py 1390 LoC,
test_dcsrmatrix/test_dcscmatrix, test_factories.py, test_manipulations.py
— VERDICT r2 #7).  Uses the reference's fixed 5x5 matrices plus scipy
ground truth for randomized sweeps across splits, formats, and dtypes.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import heat_tpu as ht

# the reference's fixtures (test_arithmetics_csr.py:20-70)
A = np.array(
    [
        [1, 0, 1, 0, 2],
        [0, 0, 2, 0, 0],
        [0, 3, 0, 2, 0],
        [2, 0, 0, 4, 0],
        [0, 3, 0, 0, 5],
    ],
    dtype=np.float32,
)
B = np.array(
    [
        [1, 0, 0, 0, 3],
        [0, 0, 2, 0, 0],
        [0, 1, 0, -1, 0],
        [2, 0, 0, 1, 0],
        [0, 0, 0, 4, 1],
    ],
    dtype=np.float32,
)


@pytest.fixture(params=[None, 0])
def split(request):
    return request.param


class TestArithmetics:
    def test_add_matches_scipy_csr(self, split):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=split)
        b = ht.sparse.sparse_csr_matrix(sp.csr_matrix(B), split=split)
        c = a + b
        want = sp.csr_matrix(A + B)
        assert isinstance(c, ht.sparse.DCSR_matrix)
        assert c.shape == (5, 5)
        np.testing.assert_allclose(c.toarray(), A + B)
        np.testing.assert_array_equal(np.asarray(c.indptr), want.indptr)
        np.testing.assert_array_equal(np.asarray(c.indices), want.indices)
        np.testing.assert_allclose(np.asarray(c.data), want.data)

    def test_mul_matches_scipy_csr(self, split):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=split)
        b = ht.sparse.sparse_csr_matrix(sp.csr_matrix(B), split=split)
        c = a * b
        want = sp.csr_matrix(A * B)
        np.testing.assert_allclose(c.toarray(), A * B)
        got = sp.csr_matrix(c.toarray())
        np.testing.assert_array_equal(got.indptr, want.indptr)

    def test_csc_add_mul(self):
        a = ht.sparse.sparse_csc_matrix(sp.csc_matrix(A), split=1)
        b = ht.sparse.sparse_csc_matrix(sp.csc_matrix(B), split=1)
        c = a + b
        assert isinstance(c, ht.sparse.DCSC_matrix)
        assert c.split == 1
        np.testing.assert_allclose(c.toarray(), A + B)
        np.testing.assert_allclose((a * b).toarray(), A * B)

    def test_mismatched_patterns_random(self):
        rng = np.random.default_rng(0)
        for trial in range(3):
            d1 = sp.random(23, 17, density=0.2, random_state=trial, format="csr")
            d2 = sp.random(23, 17, density=0.15, random_state=trial + 10, format="csr")
            a = ht.sparse.sparse_csr_matrix(d1, split=0)
            b = ht.sparse.sparse_csr_matrix(d2, split=0)
            np.testing.assert_allclose(
                (a + b).toarray(), (d1 + d2).toarray(), rtol=1e-6
            )
            np.testing.assert_allclose(
                (a * b).toarray(), d1.multiply(d2).toarray(), rtol=1e-6
            )

    def test_errors(self):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A))
        c = ht.sparse.sparse_csc_matrix(sp.csc_matrix(B))
        with pytest.raises(TypeError):
            a + c  # mixed formats (reference raises too)
        # scalar add applies to the stored values only (reference
        # sparse/_operations.py:91-99), NOT a densifying numpy-style add
        s = a + 1.0
        want = sp.csr_matrix(A).copy()
        want.data = want.data + 1.0
        np.testing.assert_allclose(s.toarray(), want.toarray())
        s2 = 1.0 + a  # __radd__
        np.testing.assert_allclose(s2.toarray(), want.toarray())
        small = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A[:3]))
        with pytest.raises(ValueError):
            a + small

    def test_matmul_family(self, split):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=split)
        b = ht.sparse.sparse_csr_matrix(sp.csr_matrix(B), split=split)
        ss = a @ b
        assert isinstance(ss, ht.sparse.DCSR_matrix)
        np.testing.assert_allclose(ss.toarray(), A @ B, rtol=1e-5)
        dense = ht.array(B, split=0)
        sd = a @ dense
        np.testing.assert_allclose(sd.numpy(), A @ B, rtol=1e-5)
        ds = dense @ a  # dense @ sparse
        np.testing.assert_allclose(ds.numpy(), B @ A, rtol=1e-5)

    def test_sum_reductions(self, split):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=split)
        np.testing.assert_allclose(float(a.sum()), A.sum(), rtol=1e-6)
        np.testing.assert_allclose(a.sum(axis=0).numpy(), A.sum(0), rtol=1e-6)
        np.testing.assert_allclose(a.sum(axis=1).numpy(), A.sum(1), rtol=1e-6)


class TestDCSRMatrix:
    """Accessor battery (reference test_dcsrmatrix.py)."""

    def test_triple_vs_scipy(self, split):
        want = sp.csr_matrix(A)
        a = ht.sparse.sparse_csr_matrix(want, split=split)
        assert a.nnz == want.nnz and a.gnnz == want.nnz
        np.testing.assert_array_equal(np.asarray(a.indptr), want.indptr)
        np.testing.assert_array_equal(np.asarray(a.global_indptr), want.indptr)
        np.testing.assert_array_equal(np.asarray(a.indices), want.indices)
        np.testing.assert_allclose(np.asarray(a.data), want.data)
        np.testing.assert_allclose(np.asarray(a.gdata), want.data)
        assert a.ndim == 2 and a.balanced

    def test_astype_transpose_repr(self):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=0)
        d = a.astype(ht.float64)
        assert d.dtype == ht.float64
        np.testing.assert_allclose(d.toarray(), A)
        t = a.T
        assert isinstance(t, ht.sparse.DCSC_matrix)
        assert t.split == 1
        np.testing.assert_allclose(t.toarray(), A.T)
        assert "DCSR_matrix" in repr(a)

    def test_counts_displs(self):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=0)
        counts, displs = a.counts_displs_nnz()
        assert sum(counts) == a.gnnz
        assert displs[0] == 0
        assert all(
            displs[i] + counts[i] == displs[i + 1] for i in range(len(counts) - 1)
        )


class TestDCSCMatrix:
    """Reference test_dcscmatrix.py: the compressed axis is the column."""

    def test_triple_vs_scipy(self):
        want = sp.csc_matrix(A)
        a = ht.sparse.sparse_csc_matrix(want, split=1)
        assert a.split == 1
        np.testing.assert_array_equal(np.asarray(a.indptr), want.indptr)
        np.testing.assert_array_equal(np.asarray(a.indices), want.indices)
        np.testing.assert_allclose(np.asarray(a.data), want.data)

    def test_transpose_roundtrip(self):
        a = ht.sparse.sparse_csc_matrix(sp.csc_matrix(A), split=1)
        back = a.T.T
        assert isinstance(back, ht.sparse.DCSC_matrix)
        np.testing.assert_allclose(back.toarray(), A)


class TestFactories:
    """Reference test_factories.py: every ingestion route."""

    def test_from_scipy_formats(self):
        for mk in (sp.csr_matrix, sp.csc_matrix, sp.coo_matrix):
            a = ht.sparse.sparse_csr_matrix(mk(A), split=0)
            np.testing.assert_allclose(a.toarray(), A)

    def test_from_dense_dndarray(self, split):
        a = ht.sparse.sparse_csr_matrix(ht.array(A, split=0), split=split)
        np.testing.assert_allclose(a.toarray(), A)
        assert a.nnz == int((A != 0).sum())

    def test_from_torch_sparse(self):
        torch = pytest.importorskip("torch")
        t = torch.tensor(A).to_sparse()
        a = ht.sparse.sparse_csr_matrix(t)
        np.testing.assert_allclose(a.toarray(), A)

    def test_dtype_override(self):
        a = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), dtype=ht.float64)
        assert a.dtype == ht.float64

    def test_csc_factory_split_validation(self):
        with pytest.raises((ValueError, NotImplementedError)):
            ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=1)
        with pytest.raises((ValueError, NotImplementedError)):
            ht.sparse.sparse_csc_matrix(sp.csc_matrix(A), split=0)


class TestManipulations:
    """Reference test_manipulations.py: conversions both ways."""

    def test_roundtrips(self, split):
        dense = ht.array(A, split=0)
        s = ht.sparse.to_sparse_csr(dense)
        assert isinstance(s, ht.sparse.DCSR_matrix)
        back = ht.sparse.to_dense(s)
        np.testing.assert_allclose(back.numpy(), A)
        c = ht.sparse.to_sparse_csc(ht.array(A, split=1))
        assert isinstance(c, ht.sparse.DCSC_matrix)
        np.testing.assert_allclose(ht.sparse.to_dense(c).numpy(), A)

    def test_to_dense_out_param(self):
        s = ht.sparse.sparse_csr_matrix(sp.csr_matrix(A), split=0)
        out = ht.empty((5, 5), dtype=ht.float32, split=0)
        res = ht.sparse.to_dense(s, out=out)
        np.testing.assert_allclose(out.numpy(), A)
        assert res is out
