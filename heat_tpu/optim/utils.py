"""Optimizer utilities, analog of heat/optim/utils.py."""

from __future__ import annotations

from typing import Dict

__all__ = ["DetectMetricPlateau"]


class DetectMetricPlateau:
    """Detect when a tracked metric plateaus (optim/utils.py:14).

    Drives DASO's warmup/cycling/cooldown phase switching
    (dp_optimizer.py:354 ``epoch_loss_logic``).  Keeps the reference's
    get_state/set_state checkpoint hooks (:72-108).
    """

    def __init__(self, mode: str = "min", patience: int = 10, threshold: float = 1e-4, threshold_mode: str = "rel"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode {mode} is unknown")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError(f"threshold mode {threshold_mode} is unknown")
        self.mode = mode
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.best = None
        self.num_bad_epochs = None
        self.mode_worse = float("inf") if mode == "min" else -float("inf")
        self.reset()

    def reset(self) -> None:
        """Reset the tracker (optim/utils.py:60)."""
        self.best = self.mode_worse
        self.num_bad_epochs = 0

    def get_state(self) -> Dict:
        """Checkpointable state dict (optim/utils.py:72)."""
        return {
            "mode": self.mode,
            "patience": self.patience,
            "threshold": self.threshold,
            "threshold_mode": self.threshold_mode,
            "best": self.best,
            "num_bad_epochs": self.num_bad_epochs,
            "mode_worse": self.mode_worse,
        }

    def set_state(self, state: Dict) -> None:
        """Restore from a state dict (optim/utils.py:90)."""
        for k, v in state.items():
            setattr(self, k, v)

    def is_better(self, a, best) -> bool:
        """Comparison under mode/threshold (optim/utils.py:110)."""
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < best * (1.0 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1.0 + self.threshold)
        return a > best + self.threshold

    def test_if_improving(self, metric) -> bool:
        """Track one value; True if the metric has plateaued
        (optim/utils.py:130)."""
        current = float(metric)
        if self.is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.num_bad_epochs = 0
            return True
        return False
