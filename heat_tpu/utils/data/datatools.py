"""Data loading tools, analog of heat/utils/data/datatools.py.

The reference wraps torch's DataLoader over the process-local chunk and
implements post-epoch cross-rank shuffles with pairwise Alltoalls
(``dataset_shuffle``/``dataset_ishuffle``, datatools.py:247-343).  Here a
:class:`Dataset` wraps the global sharded DNDarray and :class:`DataLoader`
iterates minibatches of it; the epoch shuffle is a single global
permutation (gather-free for XLA: one all-to-all under the hood).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle", "dataset_irecv"]


class Dataset:
    """Dataset over one or more aligned DNDarrays (datatools.py:144)."""

    def __init__(self, array: Union[DNDarray, Sequence[DNDarray]], transforms=None, ishuffle: bool = False):
        arrays = [array] if isinstance(array, DNDarray) else list(array)
        if not arrays:
            raise ValueError("Dataset needs at least one array")
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the sample dimension")
        self.arrays = arrays
        self.transforms = transforms if transforms is not None else []
        self.ishuffle = ishuffle

    def __len__(self) -> int:
        return self.arrays[0].shape[0]

    def __getitem__(self, index):
        items = []
        for i, a in enumerate(self.arrays):
            item = a._dense()[index]
            t = self.transforms[i] if i < len(self.transforms) and self.transforms else None
            items.append(t(item) if callable(t) else item)
        return items[0] if len(items) == 1 else tuple(items)

    def Shuffle(self) -> None:
        """Global random permutation of the sample axis (the analog of the
        reference's cross-rank Alltoall shuffle; method name matches
        ``Dataset.Shuffle``, datatools.py:200)."""
        dataset_shuffle(self)

    def Ishuffle(self) -> None:
        """Non-blocking shuffle (``Dataset.Ishuffle``, datatools.py:210)."""
        dataset_ishuffle(self)


class DataLoader:
    """Minibatch iterator over a Dataset (datatools.py:16).

    ``prefetch=N`` (overlap layer, docs/overlap.md) wraps the epoch in
    :func:`~heat_tpu.utils.data.prefetch.prefetch_to_device`: the next
    ``N`` batches are gathered and staged on device while the current one
    computes, so per-batch gather/dispatch latency hides behind the step
    instead of preceding it.  ``0`` (default) keeps the fully lazy
    iterator."""

    def __init__(
        self,
        dataset: Union[Dataset, DNDarray],
        batch_size: int = 1,
        shuffle: bool = True,
        drop_last: bool = False,
        ishuffle: bool = False,
        prefetch: int = 0,
    ):
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset)
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.ishuffle = ishuffle
        self.prefetch = int(prefetch)
        self._epoch = 0
        self._live_prefetcher = None

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self) -> Iterator:
        if self.prefetch > 0:
            from .prefetch import prefetch_to_device

            # remember the live wrapper so close() can release a
            # partially consumed (or unbounded, for stream-backed
            # datasets) epoch without draining it
            self._live_prefetcher = prefetch_to_device(self._batches(), size=self.prefetch)
            return self._live_prefetcher
        return self._batches()

    def close(self) -> None:
        """Release the most recent prefetched epoch's iterator.

        With ``prefetch=N`` the look-ahead holds the epoch generator
        (and any stream head behind the dataset) open; close() drops
        the staged buffer and closes that generator without consuming
        it — required for unbounded sources, harmless (idempotent) for
        finite epochs already exhausted."""
        p, self._live_prefetcher = self._live_prefetcher, None
        if p is not None:
            p.close()

    def _batches(self) -> Iterator:
        if self.ishuffle or getattr(self.dataset, "ishuffle", False):
            # complete the shuffle started at the end of the previous epoch
            # (the reference's DataLoader does the same Irecv-then-Ishuffle
            # cycle, datatools.py:87-101)
            dataset_irecv(self.dataset)
            dataset_ishuffle(self.dataset)
        n = len(self.dataset)
        if self.shuffle:
            from ...core import random as ht_random

            perm = np.asarray(ht_random.randperm(n)._dense())
        else:
            perm = np.arange(n)
        self._epoch += 1
        for start in range(0, n, self.batch_size):
            idx = perm[start : start + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                return
            yield self.dataset[jnp.asarray(idx)]


def dataset_shuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Shuffle the dataset's sample axis in place (datatools.py:247): the
    blocking form is the start/complete pair run back to back."""
    dataset_ishuffle(dataset, attrs)
    dataset_irecv(dataset)


def dataset_ishuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Start a non-blocking shuffle (datatools.py:305).

    JAX dispatch is asynchronous: the permutation gather below is enqueued on
    the device and this call returns before it completes.  The shuffled
    arrays are stashed on the dataset and installed by :func:`dataset_irecv`
    — the same start/complete split the reference implements with
    ``Isend``/``Irecv`` pairs.
    """
    from ...core import random as ht_random

    n = len(dataset)
    perm = ht_random.randperm(n)._dense()
    pending = []
    for a in dataset.arrays:
        shuffled = a._dense()[perm]  # enqueued, not yet materialized
        pending.append(DNDarray.from_dense(shuffled, a.split, a.device, a.comm))
    dataset._pending_shuffle = pending


def dataset_irecv(dataset: Dataset) -> None:
    """Complete a shuffle started by :func:`dataset_ishuffle`
    (datatools.py:344): wait for the enqueued permutation and install the
    shuffled arrays in place."""
    pending = getattr(dataset, "_pending_shuffle", None)
    if pending is None:
        return
    for i, a in enumerate(pending):
        jax.block_until_ready(a.larray_padded)
        dataset.arrays[i] = a
    dataset._pending_shuffle = None
