"""Sparse<->dense conversions, analog of heat/sparse/manipulations.py
(to_dense :105, to_sparse_csr/csc :51-104)."""

from __future__ import annotations

from ..core.dndarray import DNDarray
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix
from .factories import sparse_csc_matrix, sparse_csr_matrix

__all__ = ["to_dense", "to_sparse", "to_sparse_csc", "to_sparse_csr"]


def to_dense(sparse_matrix: DCSX_matrix, order=None, out=None) -> DNDarray:
    """Dense DNDarray from a sparse matrix (sparse/manipulations.py:105)."""
    if not isinstance(sparse_matrix, DCSX_matrix):
        raise TypeError(f"expected a sparse matrix, got {type(sparse_matrix)}")
    res = sparse_matrix.todense()
    if out is not None:
        out._replace(res.larray_padded)
        return out
    return res


def to_sparse_csr(array: DNDarray) -> DCSR_matrix:
    """DCSR from a dense DNDarray (sparse/manipulations.py:51)."""
    if not isinstance(array, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(array)}")
    return sparse_csr_matrix(array, split=0 if array.split == 0 else None, comm=array.comm)


def to_sparse_csc(array: DNDarray) -> DCSC_matrix:
    """DCSC from a dense DNDarray (sparse/manipulations.py:78)."""
    if not isinstance(array, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(array)}")
    return sparse_csc_matrix(array, split=1 if array.split == 1 else None, comm=array.comm)


to_sparse = to_sparse_csr
