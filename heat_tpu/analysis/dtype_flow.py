"""Jaxpr dtype-flow lint: precision hazards the promotion rules hide.

The reference framework leans on PyTorch's *runtime* type promotion;
here every program is a jaxpr first, so precision properties are checked
**statically** — the walker propagates per-value dtype through every
eqn (recursing into pjit/scan/while/cond sub-jaxprs) and emits four
diagnostics, the J2xx family (docs/static_analysis.md):

* **J201 — silent float truncation.**  A ``convert_element_type``
  narrowing a float value (f64→f32, f32→bf16/f16) that nothing
  sanctioned: at the jaxpr level an implicit promotion-narrowing and an
  explicit ``astype`` are indistinguishable, so *sanctioning is the
  declaration* — a narrowing is clean only when its target dtype is
  allowed by the active ``tolerance`` precision policy
  (:mod:`~heat_tpu.analysis.precision_policy`) or listed in
  ``allowed_narrowing``.  Weak-typed sources (Python scalars) are
  exempt (J103's domain).
* **J202 — long-axis low-precision accumulation.**  A reduction
  (``reduce_sum``/``reduce_prod``/``cum*``) or ``scan`` carry that
  accumulates in bf16/f16 over an extent >= ``HEAT_TPU_J202_THRESHOLD``
  without widening: ~8 mantissa bits swallow increments once the
  running sum outgrows them — the classic "mean over a long axis is
  garbage in bf16" bug.  (``jnp.sum`` upcasts internally; this catches
  the ``lax``-level and hand-written-kernel paths that do not.)
* **J203 — unpinned low-precision contraction.**  A ``dot_general`` /
  ``conv_general_dilated`` over bf16/f16 operands whose accumulation is
  not pinned wide: neither ``preferred_element_type`` nor a
  HIGH/HIGHEST ``precision=`` requests f32 accumulation, so the MXU
  accumulates (or XLA is free to accumulate) in the storage dtype.
* **J204 — precision-policy violation.**  With an active policy (a
  predict :func:`~heat_tpu.analysis.precision_policy.scope`, or an
  explicit ``policy=``), any float compute dtype appearing in the
  program outside the policy's ``compute_dtypes`` set.

Entry points: :func:`analyze_dtype_flow` (callable or jaxpr), used by
``program_lint.analyze`` and the ``core/dispatch.py`` compile hook, and
the ``python -m heat_tpu.analysis --rules J2`` batch mode.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import _env
from .diagnostics import Diagnostic

__all__ = ["analyze_dtype_flow", "reduction_threshold"]

#: float dtypes with <= 2-byte storage: the "low precision" set of the
#: J202/J203 accumulation rules
_LOW_FLOATS = ("bfloat16", "float16")

#: reduction primitives J202 inspects: name -> how to read the reduced
#: extent ("axes" = product over params["axes"], "axis" = shape[axis])
_REDUCE_PRIMS = {
    "reduce_sum": "axes",
    "reduce_prod": "axes",
    "cumsum": "axis",
    "cumprod": "axis",
    "cumlogsumexp": "axis",
}

_CONTRACT_PRIMS = ("dot_general", "conv_general_dilated")


def reduction_threshold() -> int:
    """The J202 extent threshold (``HEAT_TPU_J202_THRESHOLD``)."""
    return _env.env_int("HEAT_TPU_J202_THRESHOLD")


def _dtype_of(var) -> Optional[np.dtype]:
    aval = getattr(var, "aval", None)
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return None
    try:
        return np.dtype(dt)
    except TypeError:  # pragma: no cover - exotic extended dtypes
        return None


def _is_float(dt: Optional[np.dtype]) -> bool:
    if dt is None:
        return False
    try:
        return bool(jax.numpy.issubdtype(dt, np.floating))
    except TypeError:  # pragma: no cover
        return False


def _is_low_float(dt: Optional[np.dtype]) -> bool:
    return _is_float(dt) and dt.itemsize <= 2


def _sub_jaxprs(eqn):
    """Inner jaxprs of a higher-order eqn (pjit/scan/while/cond/remat)."""
    out = []
    for name in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
        sub = eqn.params.get(name)
        if sub is not None:
            out.append(sub)
    for sub in eqn.params.get("branches", ()) or ():
        out.append(sub)
    return [getattr(s, "jaxpr", s) for s in out]


def _reduced_extent(eqn) -> int:
    """Total extent the reduction runs over (1 when unreadable)."""
    kind = _REDUCE_PRIMS[eqn.primitive.name]
    shape = getattr(getattr(eqn.invars[0], "aval", None), "shape", None)
    if shape is None:
        return 1
    try:
        if kind == "axes":
            ext = 1
            for a in eqn.params.get("axes", ()) or ():
                ext *= int(shape[a])
            return ext
        return int(shape[eqn.params.get("axis", 0)])
    except (IndexError, TypeError):  # pragma: no cover - ragged params
        return 1


def _walk(
    jaxpr,
    diags: List[Diagnostic],
    label: str,
    allowed: Tuple[str, ...],
    threshold: int,
    compute_dtypes: set,
    invar_ids: set,
    depth: int = 0,
) -> None:
    if depth > 8:  # pragma: no cover - pathological nesting
        return
    # narrowest float width (bytes) that contributed to each value:
    # narrowing BACK to a source's own width (jax's internal
    # upcast-accumulate-downcast pattern, e.g. jnp.sum over bf16) loses
    # nothing the inputs had and is not a J201 hazard
    minw: Dict[int, int] = {}

    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        dt = _dtype_of(v)
        if _is_float(dt):
            minw[id(v)] = dt.itemsize

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name

        in_widths = [
            minw.get(id(v), _dtype_of(v).itemsize)
            for v in eqn.invars
            if _is_float(_dtype_of(v)) and not getattr(
                getattr(v, "aval", None), "weak_type", False
            )
        ]
        out_w = min(in_widths) if in_widths else None
        for v in eqn.outvars:
            dt = _dtype_of(v)
            if _is_float(dt):
                compute_dtypes.add(dt.name)
                minw[id(v)] = min(out_w, dt.itemsize) if out_w else dt.itemsize

        if name == "convert_element_type":
            src = eqn.invars[0]
            old = _dtype_of(src)
            new = _dtype_of(eqn.outvars[0])
            aval = getattr(src, "aval", None)
            if (
                _is_float(old)
                and _is_float(new)
                and new.itemsize < old.itemsize
                and new.itemsize < minw.get(id(src), old.itemsize)
                and not getattr(aval, "weak_type", False)
                and new.name not in allowed
            ):
                diags.append(Diagnostic(
                    rule="J201",
                    message=(
                        f"{old.name} value silently truncated to {new.name} "
                        "— no precision policy or allowed_narrowing entry "
                        "sanctions this cast; declare the low-precision "
                        "intent (a tolerance POLICIES entry + predict "
                        "scope) or keep the value wide"
                    ),
                    location=label,
                    details={"from": old.name, "to": new.name,
                             "is_input": id(src) in invar_ids},
                ))

        elif name in _REDUCE_PRIMS:
            op_dt = _dtype_of(eqn.invars[0])
            out_dt = _dtype_of(eqn.outvars[0])
            ext = _reduced_extent(eqn)
            if (
                _is_low_float(op_dt)
                and _is_low_float(out_dt)
                and ext >= threshold
            ):
                diags.append(Diagnostic(
                    rule="J202",
                    message=(
                        f"{name} accumulates {ext} elements in "
                        f"{out_dt.name} (>= threshold {threshold}) — "
                        "~8 mantissa bits swallow increments once the "
                        "running value outgrows them; accumulate in "
                        "float32 (cast before the reduction) and narrow "
                        "the result if needed"
                    ),
                    location=label,
                    details={"primitive": name, "extent": ext,
                             "dtype": out_dt.name, "threshold": threshold},
                ))

        elif name == "scan":
            nc = int(eqn.params.get("num_consts", 0) or 0)
            ncarry = int(eqn.params.get("num_carry", 0) or 0)
            length = int(eqn.params.get("length", 0) or 0)
            if length >= threshold:
                for v in eqn.invars[nc:nc + ncarry]:
                    dt = _dtype_of(v)
                    if _is_low_float(dt):
                        diags.append(Diagnostic(
                            rule="J202",
                            message=(
                                f"scan carries a {dt.name} accumulator "
                                f"through {length} steps (>= threshold "
                                f"{threshold}) — carry in float32 and "
                                "narrow on exit"
                            ),
                            location=label,
                            details={"primitive": "scan", "extent": length,
                                     "dtype": dt.name,
                                     "threshold": threshold},
                        ))
                        break

        elif name in _CONTRACT_PRIMS:
            in_dts = [_dtype_of(v) for v in eqn.invars[:2]]
            out_dt = _dtype_of(eqn.outvars[0])
            if any(_is_low_float(d) for d in in_dts) and _is_low_float(out_dt):
                prec = eqn.params.get("precision")
                prec_names = [
                    getattr(p, "name", str(p))
                    for p in (prec if isinstance(prec, (tuple, list)) else (prec,))
                    if p is not None
                ]
                pinned = any(p in ("HIGH", "HIGHEST") for p in prec_names)
                if not pinned:
                    diags.append(Diagnostic(
                        rule="J203",
                        message=(
                            f"{name} over {in_dts[0].name} operands "
                            "accumulates in the storage dtype — pass "
                            "preferred_element_type=jnp.float32 (or "
                            "precision='highest') so the MXU accumulates "
                            "wide and only the result narrows"
                        ),
                        location=label,
                        details={
                            "primitive": name,
                            "operand_dtypes": [d.name for d in in_dts if d],
                            "preferred_element_type": out_dt.name,
                        },
                    ))

        for sub in _sub_jaxprs(eqn):
            _walk(sub, diags, label, allowed, threshold, compute_dtypes,
                  invar_ids, depth + 1)


def analyze_dtype_flow(
    fn_or_jaxpr,
    *args,
    policy: Optional[Dict[str, Any]] = None,
    allowed_narrowing: Sequence[str] = (),
    label: str = "program",
    threshold: Optional[int] = None,
    **kwargs,
) -> List[Diagnostic]:
    """Walk a program's jaxpr for the J201-J204 precision hazards;
    returns the diagnostics without emitting them.

    ``fn_or_jaxpr`` is a (Closed)Jaxpr, or a callable traced at
    ``*args``/``**kwargs`` via ``jax.make_jaxpr``.  ``policy`` is a
    precision-policy document (default: the active predict scope's, via
    :func:`~heat_tpu.analysis.precision_policy.active_policy`); a
    ``tolerance`` policy's ``compute_dtypes`` sanction J201 narrowings
    into them and bound the J204 compute-dtype set.
    ``allowed_narrowing`` adds explicit extra J201-sanctioned target
    dtypes (the standalone caller's declaration)."""
    jaxpr = fn_or_jaxpr
    if callable(fn_or_jaxpr) and not hasattr(fn_or_jaxpr, "eqns"):
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    # a jitted callable traces to one pjit wrapper eqn; unwrap so the
    # invar identity set (J201's is_input detail) matches the real body
    while (
        len(jaxpr.eqns) == 1
        and jaxpr.eqns[0].primitive.name == "pjit"
        and jaxpr.eqns[0].params.get("jaxpr") is not None
    ):
        jaxpr = getattr(jaxpr.eqns[0].params["jaxpr"], "jaxpr",
                        jaxpr.eqns[0].params["jaxpr"])

    if policy is None:
        from . import precision_policy as _pp

        policy = _pp.active_policy()

    allowed = tuple(allowed_narrowing)
    if policy is not None and policy.get("mode") == "tolerance":
        allowed = allowed + tuple(policy.get("compute_dtypes") or ())
    if threshold is None:
        threshold = reduction_threshold()

    diags: List[Diagnostic] = []
    compute_dtypes: set = set()
    invar_ids = {id(v) for v in jaxpr.invars}
    _walk(jaxpr, diags, label, allowed, threshold, compute_dtypes, invar_ids)

    if policy is not None:
        dtypes = tuple(policy.get("compute_dtypes") or ("float32",))
        allowed_set = set(dtypes)
        # the policy governs the compute-dtype CHOICE, i.e. precision
        # lost below the native dtype; computing wider (f64 data fed to
        # an f32-declared estimator) IS the native path at that width
        native_size = np.dtype(dtypes[0]).itemsize
        outside = sorted(
            d for d in compute_dtypes - allowed_set
            if np.dtype(d).itemsize < native_size
        )
        if outside:
            diags.append(Diagnostic(
                rule="J204",
                message=(
                    f"program computes in {outside} but the active "
                    f"{policy.get('mode')} precision policy allows only "
                    f"{sorted(allowed_set)} — fix the compute dtype or "
                    "widen the POLICIES declaration (with a tolerance "
                    "bench)"
                ),
                location=label,
                details={"outside": outside, "policy": dict(policy)},
            ))
    return diags
