"""Graph Laplacian, analog of heat/graph/laplacian.py (laplacian.py:13-222)."""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Graph Laplacian from a pairwise similarity (laplacian.py:13).

    definition: 'simple' (L = D - A) or 'norm_sym'
    (L = I - D^-1/2 A D^-1/2); mode: 'fully_connected' or 'eNeighbour'
    with an upper/lower threshold on the similarity.
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError("Only simple and normalized symmetric Laplacians are supported, got " + definition)
        if mode not in ("fully_connected", "eNeighbour"):
            raise NotImplementedError("Only eNeighborhood and fully-connected graphs are supported, got " + mode)
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = I - D^-1/2 A D^-1/2 (laplacian.py:90)."""
        d = jnp.sum(A, axis=1)
        d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.maximum(d, 1e-30)), 0.0)
        L = -A * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]
        L = L + jnp.eye(A.shape[0], dtype=A.dtype)
        return L

    def _simple_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = D - A (laplacian.py:130)."""
        return jnp.diag(jnp.sum(A, axis=1)) - A

    def construct(self, X: DNDarray) -> DNDarray:
        """Similarity -> adjacency -> Laplacian (laplacian.py:160)."""
        S = self.similarity_metric(X)
        A = S._dense()
        if self.mode == "eNeighbour":
            if self.epsilon[0] == "upper":
                mask = A < self.epsilon[1]
            else:
                mask = A > self.epsilon[1]
            A = jnp.where(mask, A if self.weighted else jnp.ones_like(A), 0.0)
        # zero the self-loops (laplacian.py:185)
        A = A - jnp.diag(jnp.diag(A))
        if self.definition == "norm_sym":
            L = self._normalized_symmetric_L(A)
        else:
            L = self._simple_L(A)
        return DNDarray.from_dense(L, X.split, X.device, X.comm)
