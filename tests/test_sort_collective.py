"""Collective-sort guarantees (VERDICT r3 missing #5 + next-round #6):
mid-size split arrays sort via PSRS (no array-sized all-gather in the
compiled HLO), the collective reaches axis != 0 via the local moveaxis
path, and percentile/median below the old 2^22 gate ride it too.

Reference parity: heat/core/manipulations.py:2497-2750 (distributed
sample-sort at any size).
"""

import re

import jax
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import sample_sort as ss


def test_threshold_covers_midsize():
    # the r3 gate was 1<<22; a 2^20 split f64 sort must now be collective
    assert ss.SAMPLE_SORT_THRESHOLD <= 1 << 20


def test_2pow20_f64_sort_is_collective_and_correct():
    n = 1 << 20
    rng = np.random.default_rng(3)
    data = rng.standard_normal(n)
    x = ht.array(data, split=0)
    assert ss.supports_sample_sort(x, 0, False)
    v, idx = ht.sort(x)
    assert v.split == 0
    np.testing.assert_array_equal(np.asarray(v.numpy()), np.sort(data))
    np.testing.assert_array_equal(np.asarray(idx.numpy()), np.argsort(data, kind="stable"))


def _hlo_allgather_sizes(text):
    """Element counts of every all-gather result in an HLO dump."""
    sizes = []
    for m in re.finditer(r"=\s*\(?([a-z0-9]+)\[([0-9,]*)\][^)]*\)?\s*all-gather", text):
        dims = m.group(2)
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        sizes.append(count)
    return sizes


def test_psrs_hlo_has_no_array_sized_allgather():
    """The PSRS program's only all-gathers are the pivot/count exchanges
    (O(p^2) elements) — never the array (the gather path it replaces)."""
    n = 1 << 20
    x = ht.array(np.random.default_rng(0).standard_normal(n), split=0)
    comm = x.comm
    blk = x.larray_padded
    b = blk.shape[0] // comm.size
    fn = ss._psrs_fn(comm, n, b, (), str(blk.dtype), False)
    text = fn.lower(jax.ShapeDtypeStruct(blk.shape, blk.dtype)).compile().as_text()
    sizes = _hlo_allgather_sizes(text)
    assert sizes, "expected the small pivot all-gathers to be present"
    limit = max(comm.size * comm.size * 4, 1024)  # pivots/counts scale, not n
    assert all(s <= limit for s in sizes), (
        f"array-sized all-gather leaked into PSRS HLO: {sizes} (limit {limit})"
    )
    assert "all-to-all" in text  # the exchange is the all_to_all pair


@pytest.mark.parametrize("descending", [False, True])
def test_axis1_split1_sort_rides_psrs(descending):
    rows, n = 3, 1 << 18
    rng = np.random.default_rng(7)
    data = rng.standard_normal((rows, n)).astype(np.float32)
    x = ht.array(data, split=1)
    assert ss.supports_sample_sort(x, 1, descending)
    v, idx = ht.sort(x, axis=1, descending=descending)
    assert v.split == 1
    want = np.sort(data, axis=1)
    if descending:
        want = want[:, ::-1]
    np.testing.assert_array_equal(np.asarray(v.numpy()), want)
    wanti = np.argsort(-data if descending else data, axis=1, kind="stable")
    np.testing.assert_array_equal(np.asarray(idx.numpy()), wanti)


def test_axis1_matches_moveaxis_of_axis0():
    n = 1 << 18
    rng = np.random.default_rng(11)
    data = rng.standard_normal((2, n)).astype(np.float64)
    v1, i1 = ht.sort(ht.array(data, split=1), axis=1)
    v0, i0 = ht.sort(ht.array(data.T.copy(), split=0), axis=0)
    np.testing.assert_array_equal(np.asarray(v1.numpy()), np.asarray(v0.numpy()).T)
    np.testing.assert_array_equal(np.asarray(i1.numpy()), np.asarray(i0.numpy()).T)


def test_percentile_below_old_gate_uses_collective(monkeypatch):
    n = 1 << 18  # below the old 2^22 gate, above the new one
    rng = np.random.default_rng(5)
    data = rng.standard_normal(n)
    x = ht.array(data, split=0)
    calls = []
    orig = ss.sample_sort_1d
    monkeypatch.setattr(ss, "sample_sort_1d", lambda a, d=False: calls.append(1) or orig(a, d))
    got = ht.percentile(x, [10.0, 50.0, 99.5])
    assert calls, "percentile did not take the PSRS path below 2^22"
    np.testing.assert_allclose(
        np.asarray(got.numpy()), np.percentile(data, [10.0, 50.0, 99.5]), rtol=1e-12
    )
    med = ht.median(x)
    np.testing.assert_allclose(float(med), np.median(data), rtol=1e-12)
    for bad_q in (-1.0, 101.0, float("nan")):
        with pytest.raises(ValueError, match="range"):
            ht.percentile(x, bad_q)


def test_unique_below_old_gate():
    n = 1 << 18
    rng = np.random.default_rng(9)
    data = rng.integers(0, 5000, n).astype(np.int32)
    x = ht.array(data, split=0)
    got = ht.unique(x)
    np.testing.assert_array_equal(np.asarray(got.numpy()), np.unique(data))


def test_sort_out_param_same_split_no_relayout():
    n = 1 << 18
    data = np.random.default_rng(13).standard_normal(n).astype(np.float32)
    x = ht.array(data, split=0)
    out = ht.empty((n,), dtype=ht.float32, split=0)
    res, idx = ht.sort(x, out=out)
    assert res is out
    np.testing.assert_array_equal(np.asarray(out.numpy()), np.sort(data))
